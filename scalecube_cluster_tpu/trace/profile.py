"""Tick-phase profiler: the window re-run as PHASE-SPLIT jits.

The production window is one fused XLA program (``lax.scan`` over the whole
tick) — maximally fast, observably opaque: when a window is slow there is
no way to say WHICH protocol phase (FD selection, the gossip merge, SYNC's
compacted exchange, the suspicion sweep, the telemetry reductions) paid
for it. This module rebuilds the tick as a sequence of individually jitted
phase programs — same helpers, same key chain, same op spellings (the
metric tails are shared via ``kernel.state_metrics`` /
``sparse.state_metrics``) — so the final state is BIT-IDENTICAL to the
fused window while every phase gets:

* a host wall-clock measurement (``block_until_ready`` per phase), and
* a ``jax.profiler.TraceAnnotation`` scope, so a surrounding
  ``jax.profiler.trace(...)`` capture shows the phases on the device
  timeline under their protocol names.

The split run is slower than the fused one (per-phase dispatch + lost
cross-phase fusion — that is the price of the microscope and exactly why
it is a MODE, not the production path); its per-phase shares are the
honest decomposition of the split window, recorded as
``TRACE_BENCH_r10.json``'s phase breakdown and renderable as a Perfetto
timeline via :func:`..trace.export.profile_to_events`.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict, List, Tuple

#: phase names in execution order, per engine (the sparse tick has the
#: allocation-compaction "merge" phase the dense tick lacks)
DENSE_PHASES = (
    "rand", "fd", "suspicion", "gossip", "sync", "refute", "sweep",
    "telemetry",
)
SPARSE_PHASES = (
    "rand", "fd", "suspicion", "gossip", "sync", "refute", "sweep", "alloc",
    "telemetry",
)
#: pview shares the sparse phase list — its "suspicion" phase is the
#: maintenance sweep (expiry + tombstone purge + active-view promotion)
#: and its "alloc" phase is the imported sparse pool machinery
PVIEW_PHASES = SPARSE_PHASES


def _annotation(name: str):
    try:
        from jax.profiler import TraceAnnotation

        return TraceAnnotation(f"scalecube/{name}")
    except Exception:  # pragma: no cover - profiler API unavailable
        return contextlib.nullcontext()


class _Timer:
    """Accumulates per-phase wall time + the flat event timeline."""

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.timeline: List[Dict] = []
        self.t0 = time.perf_counter()

    @contextlib.contextmanager
    def phase(self, name: str, tick: int):
        import jax

        start = time.perf_counter()
        with _annotation(name):
            out = {}
            yield out
            jax.block_until_ready(out.get("v"))
        dur = time.perf_counter() - start
        self.totals[name] = self.totals.get(name, 0.0) + dur
        self.timeline.append({
            "phase": name, "tick": tick,
            "start_s": round(start - self.t0, 7), "dur_s": round(dur, 7),
        })


def _wrap_phase_fns(
    fns: Dict[str, Callable],
    fleet: bool,
    ctx_factory: Callable = None,
    spmd_axis_name: str = None,
) -> Dict[str, Callable]:
    """jit each phase callable; ``fleet=True`` vmaps it over a leading
    [S] scenario axis first (jit∘vmap — the ops/fleet.py window spelling,
    phase by phase, so the composition is bit-identical to the fleet
    window exactly as the serial split is to the serial window).

    ``ctx_factory`` (r21, the sharded phase-split builders) is entered
    INSIDE each jitted body — contexts like the pview
    ``ragged_delivery_context`` / sparse ``mesh_context`` are trace-time
    contextvars, and jit traces lazily at first call, so wrapping the jit
    call site would arm nothing. ``spmd_axis_name`` rides through to vmap
    for fleet phases on a 2-D mesh (the ``make_sharded_*_fleet_run``
    spelling, phase by phase)."""
    import jax

    def _jit(v):
        if ctx_factory is None:
            inner = v
        else:
            def inner(*args, _v=v):
                with ctx_factory():
                    return _v(*args)
        if fleet:
            return jax.jit(jax.vmap(inner, spmd_axis_name=spmd_axis_name))
        return jax.jit(inner)

    return {k: _jit(v) for k, v in fns.items()}


def _dense_phase_fns(
    params, fleet: bool = False, mesh=None, a2a_budget=None,
    spmd_axis_name: str = None,
) -> Dict[str, Callable]:
    import jax
    import jax.numpy as jnp

    from ..ops import kernel as K
    from ..ops.rand import (
        draw_fd_randoms,
        draw_round_randoms,
        split_tick_key,
    )

    def _rand(st, key):
        key, tick_key = jax.random.split(key)
        fd_key, round_key = split_tick_key(tick_key)
        r = draw_round_randoms(round_key, st.capacity, params.fanout)
        return st.replace(tick=st.tick + 1), key, fd_key, r

    def _fd(st, fd_key):
        def on(s):
            fd_r = draw_fd_randoms(fd_key, s.capacity, params.ping_req_k)
            return K._fd_phase(s, fd_r, params)

        def off(s):
            return s, {
                "fd_probes": jnp.int32(0),
                "fd_failed_probes": jnp.int32(0),
                "fd_new_suspects": jnp.int32(0),
            }

        return jax.lax.cond((st.tick % params.fd_every) == 0, on, off, st)

    return _wrap_phase_fns({
        "rand": _rand,
        "fd": _fd,
        "suspicion": lambda st: K._suspicion_phase(st, params),
        "gossip": lambda st, r: K._gossip_phase(st, r, params),
        "sync": lambda st, r: K._sync_phase(st, r, params),
        "refute": K._refute_phase,
        "sweep": lambda st: K._rumor_sweep(st, params),
        # no trace-time context: the dense sharded window is a plain jit —
        # GSPMD propagates the row sharding through each phase unchanged
        "telemetry": lambda st: K.state_metrics(st, params),
    }, fleet, spmd_axis_name=spmd_axis_name)


def _run_dense_tick(fns, timer: _Timer, state, key, t: int):
    with timer.phase("rand", t) as o:
        state, key, fd_key, r = fns["rand"](state, key)
        o["v"] = (state, key, fd_key, r)
    with timer.phase("fd", t) as o:
        state, _fd_m = fns["fd"](state, fd_key)
        o["v"] = state
    with timer.phase("suspicion", t) as o:
        state = fns["suspicion"](state)
        o["v"] = state
    with timer.phase("gossip", t) as o:
        state, _g_m = fns["gossip"](state, r)
        o["v"] = state
    with timer.phase("sync", t) as o:
        state, _s_m = fns["sync"](state, r)
        o["v"] = state
    with timer.phase("refute", t) as o:
        state = fns["refute"](state)
        o["v"] = state
    with timer.phase("sweep", t) as o:
        state = fns["sweep"](state)
        o["v"] = state
    with timer.phase("telemetry", t) as o:
        metrics = fns["telemetry"](state)
        o["v"] = metrics
    return state, key


def _sparse_phase_fns(
    params, fleet: bool = False, mesh=None, a2a_budget=None,
    spmd_axis_name: str = None,
) -> Dict[str, Callable]:
    import jax
    import jax.numpy as jnp

    from ..ops import sparse as SP
    from ..ops.rand import draw_sparse_fd, draw_sparse_round, split_tick_key

    n = params.capacity
    # the sparse sharded window's trace-time context (the word-sharded
    # apply staging reads the active mesh), entered inside each phase jit
    ctx = (lambda: SP.mesh_context(mesh)) if mesh is not None else None

    def _rand(st, key):
        key, tick_key = jax.random.split(key)
        fd_key, round_key = split_tick_key(tick_key)
        r = draw_sparse_round(round_key, n, params.fanout, params.sample_tries)
        return st.replace(tick=st.tick + 1), key, fd_key, r

    def _fd(st, fd_key):
        rows = jnp.arange(n)
        no_props = (
            jnp.zeros((n,), jnp.int32), jnp.zeros((n,), jnp.int32),
            rows, jnp.zeros((n,), bool),
        )

        def on(s):
            fd_r = draw_sparse_fd(fd_key, n, params.ping_req_k, params.sample_tries)
            return SP._fd_phase(s, fd_r, params)

        def off(s):
            return s, no_props, {
                "fd_probes": jnp.int32(0),
                "fd_failed_probes": jnp.int32(0),
                "fd_new_suspects": jnp.int32(0),
            }

        return jax.lax.cond((st.tick % params.fd_every) == 0, on, off, st)

    return _wrap_phase_fns({
        "rand": _rand,
        "fd": _fd,
        "suspicion": lambda st: SP._suspicion_sweep(st, params),
        "gossip": lambda st, r: SP._gossip_phase(st, r, params),
        "sync": lambda st, r: SP._sync_phase(st, r, params),
        "refute": lambda st: SP._refute_phase(st, params),
        "sweep": lambda st: SP._rumor_sweeps(st, params),
        "alloc": lambda st, props: SP._alloc_phase(st, props, params),
        "telemetry": lambda st: SP.state_metrics(st, params),
    }, fleet, ctx_factory=ctx, spmd_axis_name=spmd_axis_name)


def _run_sparse_tick(fns, timer: _Timer, state, key, t: int):
    with timer.phase("rand", t) as o:
        state, key, fd_key, r = fns["rand"](state, key)
        o["v"] = (state, key, fd_key, r)
    with timer.phase("fd", t) as o:
        state, props_fd, _m = fns["fd"](state, fd_key)
        o["v"] = (state, props_fd)
    with timer.phase("suspicion", t) as o:
        state, props_exp = fns["suspicion"](state)
        o["v"] = (state, props_exp)
    with timer.phase("gossip", t) as o:
        state, _g_m = fns["gossip"](state, r)
        o["v"] = state
    with timer.phase("sync", t) as o:
        state, props_sync, _s_m = fns["sync"](state, r)
        o["v"] = (state, props_sync)
    with timer.phase("refute", t) as o:
        state, props_ref = fns["refute"](state)
        o["v"] = (state, props_ref)
    with timer.phase("sweep", t) as o:
        state = fns["sweep"](state)
        o["v"] = state
    with timer.phase("alloc", t) as o:
        state, _a_m = fns["alloc"](
            state, (props_fd, props_exp, props_ref, props_sync)
        )
        o["v"] = state
    with timer.phase("telemetry", t) as o:
        metrics = fns["telemetry"](state)
        o["v"] = metrics
    return state, key


def _pview_phase_fns(
    params, fleet: bool = False, mesh=None, a2a_budget=None,
    spmd_axis_name: str = None,
) -> Dict[str, Callable]:
    import jax
    import jax.numpy as jnp

    from ..ops import pview as PV
    from ..ops.rand import draw_sparse_fd, draw_sparse_round, split_tick_key

    n = params.capacity
    # the r20 ragged-delivery rewrite, armed inside each phase jit (the
    # context is a trace-time contextvar — the sharded window builders'
    # spelling, phase by phase); None budget = the lossless default, the
    # exact context the driver's sharded windows trace under
    ctx = None
    if mesh is not None:
        from ..ops.sharding import MEMBER_AXIS

        ctx = lambda: PV.ragged_delivery_context(mesh, MEMBER_AXIS, a2a_budget)

    def _rand(st, key):
        key, tick_key = jax.random.split(key)
        fd_key, round_key = split_tick_key(tick_key)
        r = draw_sparse_round(round_key, n, params.fanout, params.sample_tries)
        return st.replace(tick=st.tick + 1), key, fd_key, r

    def _fd(st, fd_key):
        rows = jnp.arange(n)
        no_props = (
            jnp.zeros((n,), jnp.int32), jnp.zeros((n,), jnp.int32),
            rows, jnp.zeros((n,), bool),
        )

        def on(s):
            fd_r = draw_sparse_fd(
                fd_key, n, params.ping_req_k, params.sample_tries
            )
            return PV._fd_phase(s, fd_r, params)

        def off(s):
            return s, no_props, {
                "fd_probes": jnp.int32(0),
                "fd_failed_probes": jnp.int32(0),
                "fd_new_suspects": jnp.int32(0),
            }

        return jax.lax.cond((st.tick % params.fd_every) == 0, on, off, st)

    return _wrap_phase_fns({
        "rand": _rand,
        "fd": _fd,
        "suspicion": lambda st: PV._maintenance_sweep(st, params),
        "gossip": lambda st, r: PV._gossip_phase(st, r, params),
        "sync": lambda st, r: PV._sync_phase(st, r, params),
        "refute": lambda st: PV._refute_phase(st, params),
        "sweep": lambda st: PV._rumor_sweeps(st, params),
        "alloc": lambda st, props: PV._alloc_phase(st, props, params),
        "telemetry": lambda st: PV.state_metrics(st, params),
    }, fleet, ctx_factory=ctx, spmd_axis_name=spmd_axis_name)


def _run_pview_tick(fns, timer: _Timer, state, key, t: int):
    with timer.phase("rand", t) as o:
        state, key, fd_key, r = fns["rand"](state, key)
        o["v"] = (state, key, fd_key, r)
    with timer.phase("fd", t) as o:
        state, props_fd, _m = fns["fd"](state, fd_key)
        o["v"] = (state, props_fd)
    with timer.phase("suspicion", t) as o:
        state, props_exp = fns["suspicion"](state)
        o["v"] = (state, props_exp)
    with timer.phase("gossip", t) as o:
        state, _g_m = fns["gossip"](state, r)
        o["v"] = state
    with timer.phase("sync", t) as o:
        state, props_sync, _s_m = fns["sync"](state, r)
        o["v"] = (state, props_sync)
    with timer.phase("refute", t) as o:
        state, props_ref = fns["refute"](state)
        o["v"] = (state, props_ref)
    with timer.phase("sweep", t) as o:
        state = fns["sweep"](state)
        o["v"] = state
    with timer.phase("alloc", t) as o:
        state, _a_m = fns["alloc"](
            state, (props_fd, props_exp, props_ref, props_sync)
        )
        o["v"] = state
    with timer.phase("telemetry", t) as o:
        metrics = fns["telemetry"](state)
        o["v"] = metrics
    return state, key


def _engine_fns_and_runner(params, fleet: bool = False, mesh=None, a2a_budget=None):
    from ..ops.pview import PviewParams
    from ..ops.sparse import SparseParams

    spmd = None
    if mesh is not None:
        # same preconditions as the sharded window builders — fail loudly
        # up front instead of letting a misaligned shard or a Pallas
        # delivery table silently gather
        from ..ops import sharding as SH

        if fleet:
            spmd = SH.FLEET_AXIS
        if isinstance(params, PviewParams):
            SH._check_pview_word_alignment(mesh, params)
            SH._refuse_pallas_on_mesh(params)
        elif isinstance(params, SparseParams):
            SH._check_sparse_word_alignment(mesh, params)
        else:
            SH._check_dense_word_alignment(mesh, params)
    kw = dict(mesh=mesh, a2a_budget=a2a_budget, spmd_axis_name=spmd)
    if isinstance(params, PviewParams):
        return "pview", _pview_phase_fns(params, fleet, **kw), _run_pview_tick
    if isinstance(params, SparseParams):
        return "sparse", _sparse_phase_fns(params, fleet, **kw), _run_sparse_tick
    return "dense", _dense_phase_fns(params, fleet, **kw), _run_dense_tick


def profile_ticks(
    params, state, key, n_ticks: int, warmup_ticks: int = 1,
    mesh=None, a2a_budget=None,
) -> Tuple[object, object, Dict]:
    """Run ``n_ticks`` as phase-split jits; returns (state, key, result).

    The phase sequence reproduces ``tick()`` / ``sparse_tick()`` exactly
    (same helper functions, same key chain), so the returned state matches
    the fused window's bit-for-bit — tests/test_trace.py pins it. The first
    ``warmup_ticks`` compile every phase program and are EXCLUDED from the
    per-phase totals and the wall measurement.

    ``mesh`` (r21) builds the SHARDED phase programs instead: ``state``
    must already be mesh-placed (``ops.sharding.shard_*_state``), and each
    phase traces under the engine's sharded-window context (the pview
    ragged delivery rewrite with ``a2a_budget``, the sparse mesh context),
    so the split final state is bit-identical to the sharded fused window
    — tests/test_obs_mesh.py pins it."""
    engine, fns, run = _engine_fns_and_runner(params, mesh=mesh, a2a_budget=a2a_budget)
    for t in range(warmup_ticks):
        state, key = run(fns, _Timer(), state, key, t)
    timer = _Timer()
    wall0 = time.perf_counter()
    for t in range(n_ticks):
        state, key = run(fns, timer, state, key, t)
    wall = time.perf_counter() - wall0
    phase_sum = sum(timer.totals.values())
    result = {
        "engine": engine,
        "n": params.capacity,
        "mesh": (
            {str(k): int(v) for k, v in dict(mesh.shape).items()}
            if mesh is not None else None
        ),
        "ticks": n_ticks,
        "warmup_ticks": warmup_ticks,
        "wall_s": round(wall, 6),
        "phase_sum_s": round(phase_sum, 6),
        # phase coverage of the measured window wall time — the acceptance
        # gate holds this within 20% of 1.0 (the loop is phases + epsilon)
        "phase_coverage": round(phase_sum / wall, 4) if wall else None,
        "split_ticks_per_s": round(n_ticks / wall, 2) if wall else None,
        "phases_s": {k: round(v, 6) for k, v in sorted(timer.totals.items())},
        "phases_pct": {
            k: round(100.0 * v / phase_sum, 2)
            for k, v in sorted(timer.totals.items())
        } if phase_sum else {},
        "timeline": timer.timeline,
    }
    return state, key, result


def profile_fleet_ticks(
    params, fleet_state, keys, n_ticks: int, warmup_ticks: int = 1,
    mesh=None, a2a_budget=None,
) -> Tuple[object, object, Dict]:
    """Phase-split profile of a FLEET window (r15's ``jit(vmap(core))``):
    each phase program is ``jit(vmap(phase))`` over the leading [S]
    scenario axis, so the composition is bit-identical to the fleet
    window exactly as the serial split is to the serial one (vmap
    composes phase-wise; ``lax.cond`` under vmap runs both branches in
    BOTH spellings). Same result schema as :func:`profile_ticks` plus
    the scenario count ``s``; engine name suffixed ``-fleet``. ``mesh``
    (r21) must be the 2-D scenarios×members mesh the fleet state is placed
    on — each phase is then vmapped with ``spmd_axis_name`` over the
    scenario axis, the ``make_sharded_*_fleet_run`` spelling."""
    from ..ops.fleet import fleet_size

    engine, fns, run = _engine_fns_and_runner(
        params, fleet=True, mesh=mesh, a2a_budget=a2a_budget
    )
    for t in range(warmup_ticks):
        fleet_state, keys = run(fns, _Timer(), fleet_state, keys, t)
    timer = _Timer()
    wall0 = time.perf_counter()
    for t in range(n_ticks):
        fleet_state, keys = run(fns, timer, fleet_state, keys, t)
    wall = time.perf_counter() - wall0
    phase_sum = sum(timer.totals.values())
    result = {
        "engine": f"{engine}-fleet",
        "n": params.capacity,
        "mesh": (
            {str(k): int(v) for k, v in dict(mesh.shape).items()}
            if mesh is not None else None
        ),
        "s": fleet_size(fleet_state),
        "ticks": n_ticks,
        "warmup_ticks": warmup_ticks,
        "wall_s": round(wall, 6),
        "phase_sum_s": round(phase_sum, 6),
        "phase_coverage": round(phase_sum / wall, 4) if wall else None,
        "split_ticks_per_s": round(n_ticks / wall, 2) if wall else None,
        "phases_s": {k: round(v, 6) for k, v in sorted(timer.totals.items())},
        "phases_pct": {
            k: round(100.0 * v / phase_sum, 2)
            for k, v in sorted(timer.totals.items())
        } if phase_sum else {},
        "timeline": timer.timeline,
    }
    return fleet_state, keys, result


def profile_driver(driver, n_ticks: int = 32, warmup_ticks: int = 1) -> Dict:
    """Profile one driver's window WITHOUT touching its live state: the
    state and key are deep-copied (jax-owned copies — donation-safe) and
    the phase-split run happens on the copies. Returns the result dict
    (``timeline`` renders via :func:`.export.profile_to_events`)."""
    import jax
    import jax.numpy as jnp

    with driver._lock:
        state = jax.tree_util.tree_map(
            lambda x: jnp.array(x, copy=True), driver.state
        )
        key = jnp.array(driver._key, copy=True)
        if driver.mesh is not None:
            # r21 mesh lift: re-place the copies on the live shardings —
            # jnp.array gathers to one device, and the sharded phase
            # programs must see the row-sharded layout the driver runs
            # with. One host round-trip per profile call is the microscope
            # mode's price, never the production path's.
            state = jax.tree_util.tree_map(
                lambda c, live: jax.device_put(c, live.sharding),
                state, driver.state,
            )
    _st, _k, result = profile_ticks(
        driver.params, state, key, n_ticks, warmup_ticks=warmup_ticks,
        mesh=driver.mesh,
    )
    return result
