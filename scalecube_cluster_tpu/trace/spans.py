"""Sew trace-ring records into causal span trees.

Two tree shapes, matching the two causal structures the protocol has:

* **Detection lineage** — for a subject (usually a crashed tracer): the
  probe-miss → suspect-raised → expired-DEAD chain, as nested spans. This
  is the reference's per-message log trail ("which probe missed, who
  vouched, how the suspicion aged") reconstructed from the ring, and the
  explainer for every chaos detection-latency sentinel: the root span's
  extent IS the detection latency.
* **Rumor propagation tree** — for a traced user-rumor slot: the infection
  tree with per-edge provenance (who infected whom, when), the structure
  the fault-tolerant rumor-spreading analyses reason about
  (arXiv:1311.2839 §per-round trees; arXiv:1209.6158's robust push-pull).

Spans are OpenTelemetry-style plain dicts (``name`` / ``span_id`` /
``parent_span_id`` / ``start_tick`` / ``end_tick`` / ``attributes`` /
``events`` / ``children``); :mod:`.export` renders them to Chrome-trace /
Perfetto JSON. Ticks are the time base throughout (the export maps them to
microseconds).

Everything here is host-side stdlib+numpy code operating on ALREADY READ
ring snapshots — sewing never touches the device.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .schema import NO_ROW, TraceSpec, decode_records


def _span(
    name: str,
    span_id: str,
    start: int,
    end: int,
    parent: Optional[str] = None,
    **attributes,
) -> Dict:
    return {
        "name": name,
        "span_id": span_id,
        "parent_span_id": parent,
        "start_tick": int(start),
        "end_tick": int(end),
        "attributes": attributes,
        "events": [],
        "children": [],
    }


def detection_tree(events: Sequence[Dict], subject: int) -> Optional[Dict]:
    """The probe-miss → suspect → DEAD lineage of ``subject``, or None when
    the ring holds no detection activity about it.

    The chain nests: a ``detection`` root spanning first-symptom to
    detection-complete; a ``probe_miss`` child covering the failed-probe
    window; its ``suspicion`` child covering suspect-raised to the first
    expiry (with refutation events inline — a refuted episode simply has
    no ``dead`` child); and the ``dead`` grandchild covering the spread of
    the DEAD verdict across observers. Exemplar observers ride as span
    events; counts in the attributes stay exact.
    """
    mine = [e for e in events if e.get("subject") == subject]
    misses = [e for e in mine if e["kind"] == "probed" and e["missed"]]
    # suspicion: per-tick FD verdicts (the origins) + window-granular
    # gossip/SYNC dissemination summaries; same split for death (per-tick
    # expiry sweeps + window-granular spread of the verdict)
    suspects = [e for e in mine
                if e["kind"] in ("suspect_raised", "suspect_spread")]
    deads = [e for e in mine if e["kind"] in ("dead", "dead_spread")]
    refutes = [e for e in mine if e["kind"] in ("suspect_refuted", "refute")]
    if not (misses or suspects or deads):
        return None

    t_first = min(e["tick"] for e in (misses + suspects + deads))
    t_last = max(e["tick"] for e in (misses + suspects + deads + refutes))
    sid = f"detect-{subject}"
    dead_totals = [e["dead_total"] for e in deads if "dead_total" in e]
    root = _span(
        f"detection(subject={subject})", sid, t_first, t_last,
        subject=subject,
        probe_misses=sum(e["missed"] for e in misses),
        suspect_raised=sum(
            e["count"] for e in suspects if e["kind"] == "suspect_raised"
        ),
        dead_expiries=sum(e["count"] for e in deads if e["kind"] == "dead"),
        refutations=len(refutes),
        dead_total=max(dead_totals, default=0),
        detected_at=deads[-1]["tick"] if deads else None,
    )

    parent = root
    if misses:
        pm = _span(
            f"probe_miss(subject={subject})", f"{sid}-probe",
            misses[0]["tick"], misses[-1]["tick"], parent=parent["span_id"],
            first_missed_by=misses[0]["missed_by"],
            probes_missed=sum(e["missed"] for e in misses),
        )
        pm["events"] = [
            {"tick": e["tick"], "name": "probe_missed",
             "observer": e["missed_by"], "missed": e["missed"]}
            for e in misses
        ]
        parent["children"].append(pm)
        parent = pm
    if suspects:
        end = deads[0]["tick"] if deads else (
            refutes[-1]["tick"] if refutes else suspects[-1]["tick"]
        )
        peak = max(
            (e["suspect_total"] for e in suspects if "suspect_total" in e),
            default=max(e["count"] for e in suspects),
        )
        sus = _span(
            f"suspicion(subject={subject})", f"{sid}-suspect",
            suspects[0]["tick"], end, parent=parent["span_id"],
            first_suspected_by=suspects[0]["observer"],
            peak_suspect_observers=peak,
            refuted=bool(refutes and not deads),
        )
        sus["events"] = [
            {"tick": e["tick"], "name": e["kind"],
             "observer": e["observer"], "count": e["count"]}
            for e in suspects
        ] + [
            {"tick": e["tick"], "name": e["kind"]} for e in refutes
        ]
        sus["events"].sort(key=lambda e: e["tick"])
        parent["children"].append(sus)
        parent = sus
    if deads:
        dd = _span(
            f"dead(subject={subject})", f"{sid}-dead",
            deads[0]["tick"], deads[-1]["tick"], parent=parent["span_id"],
            first_expired_by=deads[0]["observer"],
            final_dead_total=max(dead_totals, default=0),
        )
        dd["events"] = [
            {
                "tick": e["tick"],
                "name": "marked_dead" if e["kind"] == "dead" else "dead_spread",
                "observer": e["observer"], "count": e["count"],
                **({"dead_total": e["dead_total"]}
                   if "dead_total" in e else {}),
            }
            for e in deads
        ]
        parent["children"].append(dd)
    return root


def rumor_tree(
    slot: int,
    origin: int,
    infected_rows: Sequence[int],
    infected_at: Sequence[int],
    infected_from: Sequence[int],
) -> Dict:
    """The per-rumor infection tree from the persistent provenance planes:
    ``infected_from[i]`` is the delivering peer (NO_ROW at the origin), so
    parent pointers ARE the tree. Returns a nested node structure rooted at
    the origin plus flat stats; nodes whose recorded parent is not itself
    infected (a reclaimed-slot edge case) attach under the root with an
    ``orphan_edge`` marker rather than being dropped."""
    nodes = {
        int(r): {"row": int(r), "at": int(a), "from": int(f), "children": []}
        for r, a, f in zip(infected_rows, infected_at, infected_from)
    }
    if origin not in nodes:
        nodes[origin] = {"row": int(origin), "at": 0, "from": NO_ROW,
                         "children": []}
    root = nodes[origin]
    depth_max = 0
    for row, node in sorted(nodes.items()):
        if row == origin:
            continue
        parent = nodes.get(node["from"])
        if parent is None or parent is node:
            node["orphan_edge"] = True
            root["children"].append(node)
        else:
            parent["children"].append(node)

    def _depth(node, d=0):
        nonlocal depth_max
        depth_max = max(depth_max, d)
        for c in node["children"]:
            _depth(c, d + 1)

    _depth(root)
    ticks = [n["at"] for n in nodes.values() if n["row"] != origin]
    return {
        "slot": int(slot),
        "origin": int(origin),
        "n_infected": len(nodes),
        "depth": depth_max,
        "first_infection_tick": min(ticks) if ticks else None,
        "last_infection_tick": max(ticks) if ticks else None,
        "root": root,
    }


def sew_trees(rows, spec: TraceSpec) -> Dict:
    """Ring rows (oldest first) -> every detection lineage the ring can
    substantiate, keyed by tracer row, plus the flat decoded event list."""
    events = decode_records(rows, spec)
    detections = {}
    for subject in spec.tracer_rows:
        tree = detection_tree(events, subject)
        if tree is not None:
            detections[int(subject)] = tree
    return {"events": events, "detections": detections}


def flatten_spans(tree: Dict) -> List[Dict]:
    """Nested span tree -> flat OTel-style span list (children resolved to
    ``parent_span_id`` references; ``children`` keys dropped)."""
    out: List[Dict] = []

    def _walk(node):
        flat = {k: v for k, v in node.items() if k != "children"}
        out.append(flat)
        for c in node["children"]:
            _walk(c)

    _walk(tree)
    return out
