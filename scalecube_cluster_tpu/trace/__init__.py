"""Causal trace plane (r10): on-device protocol span capture, tick-phase
profiling, and Perfetto/OTel export for the lockstep tensor engines.

Import surface is kept LIGHT on purpose: the tick kernels import
:mod:`.capture` from inside jitted code paths, so this ``__init__`` must
not drag in the driver-facing modules (plane/profile) — those load lazily.

* :mod:`.schema`  — ``TraceSpec`` + the ring record layout + host decode.
* :mod:`.capture` — the device-side [K, F] record builder both engines call.
* :mod:`.rings`   — the donated device trace ring (host cursor).
* :mod:`.spans`   — sew records into detection lineages + rumor trees.
* :mod:`.export`  — Chrome-trace/Perfetto JSON + OTel-style span dicts.
* :mod:`.plane`   — ``TracePlane``: the armed state of one driver.
* :mod:`.profile` — phase-split window profiler (FD/gossip/SYNC/... wall
  timings + ``jax.profiler`` annotations).
"""

from .schema import TraceSpec, decode_record, decode_records

__all__ = [
    "TraceSpec",
    "decode_record",
    "decode_records",
    "TracePlane",
]


def __getattr__(name):
    if name == "TracePlane":
        from .plane import TracePlane

        return TracePlane
    raise AttributeError(name)
