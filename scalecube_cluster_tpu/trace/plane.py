"""TracePlane: the armed causal-trace state of one :class:`..sim.SimDriver`.

Arming (``SimDriver.arm_trace``) swaps the driver's window programs for the
traced builders (``kernel.make_traced_run`` / ``sparse.make_sparse_traced_
run``) — the state trajectory stays bit-identical and the steady-state
``step()`` stays transfer-free (the ring is donated through the window;
tests/test_trace.py holds both). Everything host-facing happens at SYNC
POINTS under the driver lock, the r8 discipline: the per-window append
donates the ring buffer, so an unsynchronized monitor-thread read would
race into "Array has been deleted".

Host surfaces:

* :meth:`snapshot` / :meth:`events` / :meth:`sew` — ring readback, decode,
  span sewing (``GET /trace``).
* :meth:`detection_tree` — one subject's probe-miss → suspect → DEAD
  lineage (what chaos sentinel violations resolve to).
* :meth:`rumor_provenance` / :meth:`rumor_trees` — the full per-rumor
  infection trees from the persistent ``infected_at`` / ``infected_from``
  planes (one gather at the sync point — the ring carries per-tick
  exemplars, the planes carry the complete tree).
* :meth:`perfetto` — the Chrome-trace/Perfetto document (``GET
  /trace/perfetto``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..config import TraceConfig
from . import export as _export
from . import spans as _spans
from .rings import TraceRing
from .schema import TraceSpec, decode_records


class TracePlane:
    """The armed trace state of one driver (``driver._trace``)."""

    def __init__(
        self,
        driver,
        config: Optional[TraceConfig] = None,
        tracer_rows: Optional[Sequence[int]] = None,
        rumor_slots: Optional[Sequence[int]] = None,
    ):
        cfg = config or TraceConfig()
        cap = driver.params.capacity
        if tracer_rows is None:
            tracer_rows = tuple(cfg.tracer_rows) or tuple(
                range(min(cfg.tracers, cap))
            )
        if rumor_slots is None:
            rumor_slots = tuple(cfg.rumor_slots)
        tracer_rows = tuple(int(r) for r in tracer_rows)
        rumor_slots = tuple(int(s) for s in rumor_slots)
        if any(not 0 <= r < cap for r in tracer_rows):
            raise ValueError(f"tracer_rows out of range [0, {cap})")
        if any(not 0 <= s < driver.params.rumor_slots for s in rumor_slots):
            raise ValueError(
                f"rumor_slots out of range [0, {driver.params.rumor_slots})"
            )
        self.config = cfg
        self.driver = driver
        self.spec = TraceSpec(
            tracer_rows=tracer_rows,
            rumor_slots=rumor_slots,
            ring_len=cfg.ring_len,
            ping_req_k=driver.params.ping_req_k,
        )
        self.ring = TraceRing(self.spec)
        # window-boundary view-column mirror + summary programs (r10): the
        # dissemination diff runs OUTSIDE the window jit — an in-scan read
        # of the donated view plane costs a full extra materialization per
        # tick (capture.py's module note), while this post-window read is
        # the r8 on_window pattern, measured free.
        import jax

        from ..ops import engine_api
        from . import capture as _capture

        spec = self.spec
        # view-column source through the engine interface (r11): dense and
        # sparse gather real view-key columns; pview SYNTHESIZES them from
        # its [N, k] tables (same [N, K] i32 contract either way)
        eng = engine_api.of_driver(driver)
        tracer_rows_arr = tuple(spec.tracer_rows)

        def _summary(state, prev_cols):
            now = eng.tracer_view_cols(state, tracer_rows_arr)
            rows = _capture.build_summary_rows(
                spec, state.tick, state.up, prev_cols, now
            )
            return rows, now

        self._summary_fn = jax.jit(_summary)
        self._append_fn = jax.jit(
            lambda buf, rows, cur: _capture.append_rows(
                buf, cur, rows, spec.ring_len
            )[0],
            donate_argnums=0,
        )
        self._gather_cols = jax.jit(
            lambda state: eng.tracer_view_cols(state, tracer_rows_arr)
        )
        self._cols = self._gather_cols(driver.state)
        # per-k snapshot cache keyed by append counters (r19): appends only
        # happen inside the lock-holding window dispatch, so between window
        # boundaries the ring cannot change and a scrape can serve the
        # retained host copy without touching the driver lock
        self._snap_cache: Dict[object, tuple] = {}

    # -- the per-window device path (called under the driver lock) -----------
    def on_window(self, state) -> None:
        """Fold one window boundary into the ring: the view-column diff
        since the previous boundary as a FLAG_SUMMARY record block. Pure
        device ops — zero device→host transfers."""
        rows, self._cols = self._summary_fn(state, self._cols)
        self.ring.buf = self._append_fn(
            self.ring.buf, rows, self.ring.device_cursor()
        )
        self.ring.advance(self.spec.n_tracers)

    def reset_cols(self, state) -> None:
        """Re-baseline the window-boundary mirror (driver restore: the old
        columns belong to the abandoned timeline)."""
        self._cols = self._gather_cols(state)

    def on_restore(self, state) -> None:
        """Driver restore: clear the ring AND re-baseline the mirror — a
        restored driver's tick counter rewinds, and decode orders records
        by tick, so retained records from the abandoned timeline would sew
        into the restored one as phantom lineage (the same class the
        driver's watch re-baseline prevents)."""
        self.ring.clear()
        self.reset_cols(state)

    # -- stats (host-only; no device touch) -----------------------------------
    def stats(self) -> Dict:
        return {
            "tracer_rows": list(self.spec.tracer_rows),
            "rumor_slots": list(self.spec.rumor_slots),
            "ring_len": self.spec.ring_len,
            "n_fields": self.spec.n_fields,
            "records": self.ring.records,
            "records_total": self.ring.records_total,
            "cursor": self.ring.cursor,
            "wraps": self.ring.wraps,
            "ticks_retained": self.spec.ring_len // self.spec.n_tracers,
        }

    # -- sync points (driver lock + readback bookkeeping) ---------------------
    def snapshot(self, k: Optional[int] = None) -> Dict:
        """Raw ring readback, oldest first — THE trace-ring sync point.

        Cached per (append-count, k): a ``/trace`` scrape landing while a
        mega-sim window holds the driver lock serves the newest COMPLETE
        window's host copy immediately (r19 serving SLO) instead of
        waiting out the window's compute; only the first read after a
        window boundary pays the lock + transfer. ``records`` joins
        ``records_total`` in the key so the restore-path ``clear()``
        (which rewinds ``records`` but not the lifetime total)
        invalidates retained pre-restore rows."""
        key = (self.ring.records_total, self.ring.records, k)
        hit = self._snap_cache.get(k)
        if hit is not None and hit[0] == key:
            return hit[1]
        with self.driver._lock:
            snap = self.ring.snapshot(k)
        self.driver._note_readback(1)
        self._snap_cache[k] = (key, snap)
        return snap

    def events(self, k: Optional[int] = None) -> List[Dict]:
        """Decoded protocol events from the newest ``k`` records."""
        return decode_records(self.snapshot(k)["rows"], self.spec)

    def sew(self, k: Optional[int] = None) -> Dict:
        """Events + every detection lineage the ring substantiates."""
        return _spans.sew_trees(self.snapshot(k)["rows"], self.spec)

    def detection_tree(self, subject: int, k: Optional[int] = None):
        """The probe-miss → suspect → DEAD span tree of one tracer subject
        (None when the ring holds no detection activity about it)."""
        return _spans.detection_tree(self.events(k), subject)

    # -- rumor provenance (persistent planes, one gather) ---------------------
    def rumor_provenance(self, slot: int) -> Dict:
        """The complete infection record of one traced slot from the
        persistent planes: rows, arrival ticks, infecting edges."""
        if slot not in self.spec.rumor_slots:
            raise ValueError(f"slot {slot} is not traced ({self.spec.rumor_slots})")
        d = self.driver
        with d._lock:
            st = d.state
            inf_plane = getattr(st, "infected_bool", st.infected)
            inf = np.asarray(inf_plane[:, slot])
            at = np.asarray(st.infected_at[:, slot])
            frm = np.asarray(st.infected_from[:, slot])
            origin = int(np.asarray(st.rumor_origin[slot]))
        d._note_readback(1)
        rows = np.nonzero(inf)[0]
        return {
            "slot": int(slot),
            "origin": origin,
            "rows": [int(r) for r in rows],
            "at": [int(a) for a in at[rows]],
            "from": [int(f) for f in frm[rows]],
        }

    def rumor_trees(self) -> List[Dict]:
        """Infection trees for every traced slot (empty slots excluded)."""
        trees = []
        for slot in self.spec.rumor_slots:
            prov = self.rumor_provenance(slot)
            if prov["rows"]:
                trees.append(_spans.rumor_tree(
                    prov["slot"], prov["origin"], prov["rows"], prov["at"],
                    prov["from"],
                ))
        return trees

    # -- monitor surfaces ------------------------------------------------------
    def trace_snapshot(self, k: int = 256) -> Dict:
        """``GET /trace``: stats + the newest ``k`` records decoded + sewn
        detection lineages (JSON-ready)."""
        sewn = self.sew(k)
        return {
            "armed": True,
            **self.stats(),
            "engine": self.driver.engine,
            "events": sewn["events"],
            "detections": sewn["detections"],
        }

    def perfetto(self, k: Optional[int] = None, profile: Optional[Dict] = None) -> Dict:
        """``GET /trace/perfetto``: the combined Chrome-trace document —
        protocol span trees + rumor infection trees (+ an optional
        phase-profiler timeline when the caller ran one)."""
        sewn = self.sew(k)
        return _export.chrome_trace(
            span_trees=list(sewn["detections"].values()),
            rumor_trees=self.rumor_trees(),
            profile=profile,
            tick_us=self.config.tick_us,
        )

    def otel_spans(self, k: Optional[int] = None) -> List[Dict]:
        """OpenTelemetry-style span dicts for every sewn lineage."""
        sewn = self.sew(k)
        return _export.to_otel_spans(list(sewn["detections"].values()))

    # -- flight-recorder section ----------------------------------------------
    def flight_section(self, violating_rows: Sequence[int] = (),
                       tail: int = 256) -> Dict:
        """What a flight dump carries (r10 satellite): the trace-ring tail
        (raw rows — replayable through :func:`..trace.schema
        .decode_records`) plus the sewn span tree for each violating member
        that is a tracer, so post-mortems carry causality."""
        snap = self.snapshot(tail)
        events = decode_records(snap["rows"], self.spec)
        trees = {}
        for row in violating_rows:
            if row in self.spec.tracer_rows:
                tree = _spans.detection_tree(events, int(row))
                if tree is not None:
                    trees[int(row)] = tree
        return {
            "fields": snap["fields"],
            "records_total": snap["records"],
            "rows": [[int(v) for v in r] for r in snap["rows"]],
            "tracer_rows": list(self.spec.tracer_rows),
            "rumor_slots": list(self.spec.rumor_slots),
            "span_trees": trees,
        }
