"""Device-side trace-record builders, shared by BOTH tick engines.

Two record paths, split by COST STRUCTURE (measured, not aesthetic):

* **Per-tick rows** (:func:`build_trace_rows`, called inside the window
  jit): everything derivable from [N]-sized phase internals the tick
  already computed — FD probe picks/outcomes and verdict suspicions, the
  suspicion sweep's expiry transitions (exported from the sweep branch's
  own temp), self-refutations, SYNC caller outcomes, and per-slot rumor
  first-infection exemplars. These add no measurable cost: no new
  full-plane work, no extra consumers of the carried [N, N] planes.
* **Per-window summary rows** (:func:`build_summary_rows`, run by the
  driver OUTSIDE the window jit at the window boundary): the
  window-over-window diff of the tracers' view-key COLUMNS — suspicion /
  death dissemination across observers, observed refutations, running
  totals. The diff lives outside the window program because ANY in-scan
  consumer of the donated view plane (a column gather, even behind a
  lax.cond) statically forces an extra full-plane materialization per
  tick — measured at ~18% of the N=4096 CPU tick. At the window boundary
  the read is the r8 telemetry-plane pattern (``on_window`` consuming the
  post-window state), which config8/config10 measure as free.

Everything is pure jnp on values the tick already computed: capture reads
the trajectory, never feeds back into it, which is what makes the
armed-vs-unarmed bit-identical lockstep provable rather than hoped
(tests/test_trace.py pins it for both engines).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.lattice import RANK_ALIVE, RANK_DEAD, RANK_SUSPECT
from .schema import (
    FLAG_FD_ROUND,
    FLAG_PROBE_ACK,
    FLAG_PROBE_DIRECT,
    FLAG_PROBE_SENT,
    FLAG_SELF_REFUTED,
    FLAG_SUMMARY,
    FLAG_SYNC_DUE,
    FLAG_SYNC_OK,
    NO_ROW,
    TraceSpec,
)


def zero_fd_trace(n: int, k: int) -> dict:
    """Structure-matched zeros for the FD phase's off-tick ``lax.cond``
    branch (no probes happened; every derived event decodes to nothing)."""
    return {
        "tgt": jnp.zeros((n,), jnp.int32),
        "has_tgt": jnp.zeros((n,), bool),
        "ack": jnp.zeros((n,), bool),
        "direct_ok": jnp.zeros((n,), bool),
        "suspect": jnp.zeros((n,), bool),
        "relays": jnp.zeros((n, k), jnp.int32),
        "relay_valid": jnp.zeros((n, k), bool),
        "relay_ok": jnp.zeros((n, k), bool),
    }


def zero_sus_trace(spec: TraceSpec) -> dict:
    """Zeros for the suspicion sweep's skip branch: no expiries."""
    k = spec.n_tracers
    return {
        "count": jnp.zeros((k,), jnp.int32),
        "by": jnp.full((k,), NO_ROW, jnp.int32),
    }


def expiry_trace(expired: jax.Array, spec: TraceSpec) -> dict:
    """Per-tracer expiry export, computed INSIDE the sweep branch from its
    already-materialized ``expired`` [N, N] temp (reading a branch temp is
    free; reading the carried view plane is not — see the module note)."""
    tr = jnp.asarray(spec.tracer_rows, jnp.int32)
    cols = expired[:, tr]  # [N, K]
    return {
        "count": cols.sum(axis=0).astype(jnp.int32),
        "by": _exemplar(cols),
    }


def gather_tracer_cols(view_key: jax.Array, spec: TraceSpec) -> jax.Array:
    """The tracers' [N, K] view-key columns as i32 (narrow i16 keys are
    widened so the diff math is layout-independent). Window-boundary use
    ONLY — never call this inside the window jit (the cost note above)."""
    tr = jnp.asarray(spec.tracer_rows, jnp.int32)
    return view_key[:, tr].astype(jnp.int32)


def _exemplar(mask: jax.Array) -> jax.Array:
    """Lowest set row per column of an [N, K] mask (NO_ROW when empty) —
    the deterministic exemplar the wide-row schema records when an event
    class bursts past one observer per tick."""
    n = mask.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    ex = jnp.where(mask, rows[:, None], n).min(axis=0)
    return jnp.where(ex >= n, NO_ROW, ex).astype(jnp.int32)


def build_trace_rows(
    spec: TraceSpec,
    *,
    tick: jax.Array,
    up: jax.Array,
    fd_ran: jax.Array,
    trace_fd: dict,
    trace_sus: dict,
    trace_ref: jax.Array,
    trace_sync: dict,
    infected_b: jax.Array,
    infected_at: jax.Array,
    infected_from: jax.Array,
) -> jax.Array:
    """One tick's [K, n_fields] int32 record block (see :mod:`.schema`).

    ``trace_fd`` / ``trace_sus`` / ``trace_sync`` are the engines'
    phase-internal exports; ``trace_ref`` the [K] self-refuted mask;
    ``infected_*`` the post-tick rumor planes ([N, R] bool/i32).
    """
    k = spec.ping_req_k
    tr = jnp.asarray(spec.tracer_rows, jnp.int32)  # [K]
    K = spec.n_tracers
    i32 = jnp.int32
    zero_k = jnp.zeros((K,), i32)

    # -- tracer as observer: the FD probe ------------------------------------
    tgt = trace_fd["tgt"].astype(i32)
    has_tgt = trace_fd["has_tgt"] & fd_ran
    ack = trace_fd["ack"]
    probe_sent = has_tgt[tr]
    probe_tgt = jnp.where(probe_sent, tgt[tr], NO_ROW)
    probe_ack = probe_sent & ack[tr]
    probe_direct = probe_sent & trace_fd["direct_ok"][tr]
    # vouch requests fire only when the direct ping failed (the reference's
    # doPingReq path); a direct ack's relays were never asked
    indirect = probe_sent & ~probe_direct
    relay_rows = jnp.where(
        indirect[:, None] & trace_fd["relay_valid"][tr],
        trace_fd["relays"][tr].astype(i32),
        NO_ROW,
    )  # [K, k]
    vouch_mask = jnp.where(
        indirect[:, None] & trace_fd["relay_ok"][tr],
        1 << jnp.arange(k, dtype=i32)[None, :],
        0,
    ).sum(axis=1).astype(i32)

    # -- tracer as subject: probes + FD suspect verdicts about it ------------
    probed = has_tgt[:, None] & (tgt[:, None] == tr[None, :])  # [N, K]
    probed_by = probed.sum(axis=0).astype(i32)
    miss = probed & ~ack[:, None]
    probed_miss = miss.sum(axis=0).astype(i32)
    probed_miss_by = _exemplar(miss)
    sus_verdict = probed & trace_fd["suspect"][:, None]
    new_suspect = sus_verdict.sum(axis=0).astype(i32)
    new_suspect_by = _exemplar(sus_verdict)

    # -- tracer as SYNC caller ------------------------------------------------
    caller = trace_sync["caller"].astype(i32)
    sync_valid = trace_sync["valid"]
    m = (caller[None, :] == tr[:, None]) & sync_valid[None, :]  # [K, Ks]
    sync_due = m.any(axis=1)
    slot = jnp.argmax(m, axis=1)
    sync_ok = sync_due & trace_sync["ok"][slot]
    sync_peer = jnp.where(sync_ok, trace_sync["peer"].astype(i32)[slot], NO_ROW)
    sync_req_acc = jnp.where(sync_ok, trace_sync["req_acc"].astype(i32)[slot], 0)
    sync_ack_acc = jnp.where(sync_ok, trace_sync["ack_acc"].astype(i32)[slot], 0)

    # -- header flags ---------------------------------------------------------
    def _bit(cond, bit):
        return jnp.where(cond, i32(bit), i32(0))

    flags = (
        _bit(fd_ran, FLAG_FD_ROUND)
        + _bit(probe_sent, FLAG_PROBE_SENT)
        + _bit(probe_ack, FLAG_PROBE_ACK)
        + _bit(probe_direct, FLAG_PROBE_DIRECT)
        + _bit(trace_ref & up[tr], FLAG_SELF_REFUTED)
        + _bit(sync_due, FLAG_SYNC_DUE)
        + _bit(sync_ok, FLAG_SYNC_OK)
    )

    fields = [
        jnp.broadcast_to(tick.astype(i32), (K,)),
        tr,
        flags,
        probe_tgt,
        vouch_mask,
    ]
    fields += [relay_rows[:, s] for s in range(k)]
    fields += [
        probed_by,
        probed_miss,
        probed_miss_by,
        new_suspect,
        new_suspect_by,
        zero_k,  # suspect_total: summary rows only
        trace_sus["count"],
        trace_sus["by"],
        zero_k,  # dead_total: summary rows only
        zero_k,  # refute_seen: summary rows only
        sync_peer,
        sync_req_acc,
        sync_ack_acc,
    ]

    # -- traced rumor slots (slot-scoped; replicated across tracer rows) -----
    for slot_id in spec.rumor_slots:
        newly = infected_b[:, slot_id] & (infected_at[:, slot_id] == tick) & up
        count = newly.sum().astype(i32)
        node = _exemplar(newly[:, None])[0]
        src = jnp.where(
            node >= 0, infected_from[jnp.maximum(node, 0), slot_id], NO_ROW
        ).astype(i32)
        fields += [
            jnp.broadcast_to(count, (K,)),
            jnp.broadcast_to(node, (K,)),
            jnp.broadcast_to(src, (K,)),
        ]

    assert len(fields) == spec.n_fields, (len(fields), spec.n_fields)
    return jnp.stack(fields, axis=1)


def build_summary_rows(
    spec: TraceSpec,
    tick: jax.Array,
    up: jax.Array,
    prev_cols: jax.Array,
    now_cols: jax.Array,
) -> jax.Array:
    """One window-boundary [K, n_fields] summary block (FLAG_SUMMARY): the
    view-column diff since the previous boundary — dissemination counts,
    exemplars, and running totals. Runs OUTSIDE the window jit (driver
    ``TracePlane.on_window``); transitions are captured no matter which
    phase caused them, at window granularity."""
    K = spec.n_tracers
    i32 = jnp.int32
    tr = jnp.asarray(spec.tracer_rows, i32)
    zero_k = jnp.zeros((K,), i32)
    no_row = jnp.full((K,), NO_ROW, i32)
    up_col = up[:, None]

    known_prev = prev_cols >= 0
    known_now = now_cols >= 0
    sus_prev = known_prev & ((prev_cols & 3) == RANK_SUSPECT)
    sus_now = known_now & ((now_cols & 3) == RANK_SUSPECT)
    dead_prev = known_prev & ((prev_cols & 3) == RANK_DEAD)
    dead_now = known_now & ((now_cols & 3) == RANK_DEAD)
    new_suspect = up_col & sus_now & ~sus_prev
    new_dead = up_col & dead_now & ~dead_prev
    refute_seen = (
        up_col
        & sus_prev
        & known_now
        & ((now_cols & 3) == RANK_ALIVE)
        & (now_cols > prev_cols)
    )

    fields = [
        jnp.broadcast_to(tick.astype(i32), (K,)),
        tr,
        jnp.full((K,), FLAG_SUMMARY, i32),
        no_row,  # probe_tgt
        zero_k,  # vouch_mask
    ]
    fields += [no_row for _ in range(spec.ping_req_k)]
    fields += [
        zero_k,  # probed_by
        zero_k,  # probed_miss
        no_row,  # probed_miss_by
        new_suspect.sum(axis=0).astype(i32),
        _exemplar(new_suspect),
        (up_col & sus_now).sum(axis=0).astype(i32),
        new_dead.sum(axis=0).astype(i32),
        _exemplar(new_dead),
        (up_col & dead_now).sum(axis=0).astype(i32),
        refute_seen.sum(axis=0).astype(i32),
        no_row,  # sync_peer
        zero_k,  # sync_req_accepts
        zero_k,  # sync_ack_accepts
    ]
    fields += [zero_k] * (3 * len(spec.rumor_slots))
    assert len(fields) == spec.n_fields, (len(fields), spec.n_fields)
    return jnp.stack(fields, axis=1)


def append_rows(
    buf: jax.Array, cursor: jax.Array, rows: jax.Array, ring_len: int
) -> tuple[jax.Array, jax.Array]:
    """Circular append of one [K, F] block at the cursor; returns (buf,
    advanced cursor). Used both inside the window scan (device-carried
    cursor) and by the driver's window-boundary summary append (host
    cursor uploaded) — the HOST mirrors the count either way, so reading
    the ring never needs a device round trip to find it."""
    K = rows.shape[0]
    idx = (cursor + jnp.arange(K, dtype=jnp.int32)) % ring_len
    return buf.at[idx].set(rows), (cursor + K) % ring_len
