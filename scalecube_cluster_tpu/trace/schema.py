"""Trace-ring record schema: THE shared spelling of the causal trace plane.

One ring record is a wide int32 row describing everything protocol-visible
that happened to ONE tracer member in ONE tick — the reference gets the
same information for free from per-message DEBUG logs on its Reactor
pipeline (``FailureDetectorImpl`` / ``GossipProtocolImpl`` logging, SURVEY
§5); the lockstep tensor engine captures it as a fixed-shape device append
instead (``[ring_len, n_fields]`` int32, the r8 metric-ring discipline:
appended inside the window jit, HOST cursor, transferred only at a
flush/scrape sync point).

Why a wide row per (tracer, tick) instead of one narrow row per event: the
number of protocol events per tick is data-dependent, and a data-dependent
append count would force a DEVICE cursor — and with it either a per-window
readback (breaking the r6 zero-transfer discipline) or dynamic shapes
(breaking jit). A static row per tracer per tick keeps the append count a
host-known constant (K rows per tick), at the cost of exemplar sampling
for event classes that can burst (see the ``*_BY`` fields: counts are
exact, the named observer is the lowest-row exemplar).

Field groups (offsets depend on the static ``ping_req_k`` and the traced
rumor-slot count — always go through :class:`TraceSpec`):

* header — tick, tracer row, flags (FD round ran / probe sent / acked /
  direct / self-refuted / SYNC due / SYNC ok).
* tracer as OBSERVER — its FD probe (target, ack path, vouch verdict
  bitmask, relay rows = the vouch requests) and its SYNC round (peer,
  records the peer accepted from its table, records it accepted back).
* tracer as SUBJECT — who probed it and who missed (count + exemplar),
  suspicion raised / refuted / expired→DEAD transitions in observer
  tables about it (counts + exemplars + running totals), derived by
  diffing the tracer's view-key COLUMN across the tick, so a transition
  is captured no matter which phase (FD verdict, gossip merge, SYNC
  merge, suspicion sweep) caused it.
* traced rumor slots — per-slot first-infection activity this tick
  (count, exemplar infectee, its infecting edge from ``infected_from`` —
  the per-rumor propagation-tree lineage of the fault-tolerant
  rumor-spreading analyses, arXiv:1311.2839 / arXiv:1209.6158). The FULL
  infection tree additionally rides the persistent ``infected_at`` /
  ``infected_from`` planes, gathered at sync points
  (:meth:`..trace.plane.TracePlane.rumor_provenance`).

Everything here is host-importable without jax (numpy only) — spans.py and
export.py decode records on the monitor thread.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple

#: sentinel for "no row" in row-valued fields
NO_ROW = -1

# header flag bits (F_FLAGS)
FLAG_FD_ROUND = 1 << 0  # the FD phase ran this tick (tick % fd_every == 0)
FLAG_PROBE_SENT = 1 << 1  # tracer had a probe target this round
FLAG_PROBE_ACK = 1 << 2  # the probe round-trip succeeded (direct or vouched)
FLAG_PROBE_DIRECT = 1 << 3  # ...via the direct ping (no vouch needed)
FLAG_SELF_REFUTED = 1 << 4  # tracer bumped its own incarnation this tick
FLAG_SYNC_DUE = 1 << 5  # tracer held a SYNC caller slot this tick
FLAG_SYNC_OK = 1 << 6  # ...and the SYNC round trip landed
#: WINDOW-SUMMARY record (appended once per window by the driver, not per
#: tick by the kernel): the subject-group fields hold the window-over-window
#: view-column diff — suspicion/death/refutation SPREAD across observers and
#: the running totals. Per-tick rows carry the event ORIGINS instead (FD
#: verdicts, expiry sweeps, self-refutations), captured from phase
#: internals: an in-scan read of the donated [N, N] view plane costs a full
#: extra plane materialization per tick (~18% at N=4096 CPU — measured, not
#: guessed), so the column diff runs OUTSIDE the window jit at the window
#: boundary, where the r8 telemetry plane already proved the pattern free.
FLAG_SUMMARY = 1 << 7

# fixed header fields
F_TICK = 0
F_TRACER = 1
F_FLAGS = 2
F_PROBE_TGT = 3  # tracer's probe target this FD round (NO_ROW = none)
F_VOUCH_MASK = 4  # bit s set = relay s acked the indirect probe
_HEADER_FIELDS = 5

#: per-relay vouch-request fields follow the header (ping_req_k of them),
#: then the as-subject group, then the SYNC group, then 3 per traced slot.
#: tick rows: new_suspect = FD-verdict suspicions raised about the tracer
#: this round (the lineage ORIGIN events); new_dead = suspicion-expiry
#: transitions this tick (the sweep that turns SUSPECT into DEAD); the
#: totals/refute_seen are 0. Summary rows (FLAG_SUMMARY): the same fields
#: hold the window-over-window view-column diff — gossip/SYNC-spread
#: suspicion ("who else now suspects"), death dissemination, observed
#: refutations, and the running suspect/dead observer totals.
_SUBJECT_FIELDS = (
    "probed_by",  # up observers that probed the tracer this round
    "probed_miss",  # ...whose probe round failed (the probe-miss events)
    "probed_miss_by",  # exemplar failing observer (lowest row; NO_ROW none)
    "new_suspect",  # tick: FD suspect verdicts; summary: newly-SUSPECT cells
    "new_suspect_by",  # exemplar suspecting observer
    "suspect_total",  # summary only: up observers holding SUSPECT on tracer
    "new_dead",  # tick: expiry transitions; summary: newly-DEAD cells
    "new_dead_by",  # exemplar observer
    "dead_total",  # summary only: up observers holding DEAD on tracer
    "refute_seen",  # summary only: cells flipped SUSPECT -> higher ALIVE
)
_SYNC_FIELDS = (
    "sync_peer",  # peer of the tracer's SYNC round (NO_ROW = none/undue)
    "sync_req_accepts",  # records the peer accepted from the tracer's table
    "sync_ack_accepts",  # records the tracer accepted from the ACK table
)
_RUMOR_FIELDS = ("rumor_new_inf", "rumor_inf_node", "rumor_inf_src")


@dataclasses.dataclass(frozen=True)
class TraceSpec:
    """Static (hashable — it parameterizes jitted window programs) shape of
    one armed trace plane: WHO is traced and how much history is retained.

    ``tracer_rows`` — the K sampled tracer members (both their outbound
    protocol activity and everything other members do ABOUT them is
    captured). ``rumor_slots`` — the T traced user-rumor slots.
    ``ring_len`` — device ring rows retained (K rows append per tick, so
    the ring holds ``ring_len / K`` ticks of history). ``ping_req_k``
    mirrors the engine's relay count and fixes the vouch-field width.
    """

    tracer_rows: Tuple[int, ...]
    rumor_slots: Tuple[int, ...] = ()
    ring_len: int = 8192
    ping_req_k: int = 3

    def __post_init__(self):
        if not self.tracer_rows:
            raise ValueError("TraceSpec needs at least one tracer row")
        if len(set(self.tracer_rows)) != len(self.tracer_rows):
            raise ValueError("tracer_rows must be distinct")
        if len(set(self.rumor_slots)) != len(self.rumor_slots):
            raise ValueError("rumor_slots must be distinct")
        if self.ring_len < len(self.tracer_rows):
            raise ValueError(
                "ring_len must hold at least one tick of records "
                f"({len(self.tracer_rows)} tracer rows)"
            )

    @property
    def n_tracers(self) -> int:
        return len(self.tracer_rows)

    @property
    def n_fields(self) -> int:
        return (
            _HEADER_FIELDS
            + self.ping_req_k
            + len(_SUBJECT_FIELDS)
            + len(_SYNC_FIELDS)
            + 3 * len(self.rumor_slots)
        )

    # -- field offsets --------------------------------------------------------
    def relay_field(self, s: int) -> int:
        """Row of the s-th vouch request (the relay the tracer asked)."""
        return _HEADER_FIELDS + s

    def subject_field(self, name: str) -> int:
        return _HEADER_FIELDS + self.ping_req_k + _SUBJECT_FIELDS.index(name)

    def sync_field(self, name: str) -> int:
        return (
            _HEADER_FIELDS
            + self.ping_req_k
            + len(_SUBJECT_FIELDS)
            + _SYNC_FIELDS.index(name)
        )

    def rumor_field(self, t: int, name: str) -> int:
        """Field of rumor group ``t`` (the t-th TRACED slot, not the slot
        id); identical values are written to every tracer's row."""
        return (
            _HEADER_FIELDS
            + self.ping_req_k
            + len(_SUBJECT_FIELDS)
            + len(_SYNC_FIELDS)
            + 3 * t
            + _RUMOR_FIELDS.index(name)
        )

    def field_names(self) -> List[str]:
        names = ["tick", "tracer", "flags", "probe_tgt", "vouch_mask"]
        names += [f"vouch_relay{s}" for s in range(self.ping_req_k)]
        names += list(_SUBJECT_FIELDS)
        names += list(_SYNC_FIELDS)
        for slot in self.rumor_slots:
            names += [f"{n}_s{slot}" for n in _RUMOR_FIELDS]
        return names


def decode_record(row: Sequence[int], spec: TraceSpec) -> List[Dict]:
    """One ring row -> the list of protocol EVENTS it encodes (host-side;
    plain dicts, JSON-ready). Empty groups decode to no events, so a quiet
    tick's row vanishes here rather than polluting the span stream."""
    row = [int(v) for v in row]
    tick = row[F_TICK]
    tracer = row[F_TRACER]
    flags = row[F_FLAGS]
    sf = lambda n: row[spec.subject_field(n)]  # noqa: E731
    events: List[Dict] = []

    if flags & FLAG_SUMMARY:
        # window-boundary view-diff record: dissemination of the verdicts
        # across observers + running totals (see FLAG_SUMMARY)
        if sf("new_suspect"):
            events.append({
                "kind": "suspect_spread",
                "tick": tick,
                "subject": tracer,
                "count": sf("new_suspect"),
                "observer": sf("new_suspect_by"),
                "suspect_total": sf("suspect_total"),
            })
        if sf("new_dead"):
            events.append({
                "kind": "dead_spread",
                "tick": tick,
                "subject": tracer,
                "count": sf("new_dead"),
                "observer": sf("new_dead_by"),
                "dead_total": sf("dead_total"),
            })
        if sf("refute_seen"):
            events.append({
                "kind": "suspect_refuted",
                "tick": tick,
                "subject": tracer,
                "count": sf("refute_seen"),
            })
        return events

    if flags & FLAG_PROBE_SENT:
        relays = [
            row[spec.relay_field(s)]
            for s in range(spec.ping_req_k)
            if row[spec.relay_field(s)] != NO_ROW
        ]
        events.append({
            "kind": "probe",
            "tick": tick,
            "observer": tracer,
            "subject": row[F_PROBE_TGT],
            "ack": bool(flags & FLAG_PROBE_ACK),
            "direct": bool(flags & FLAG_PROBE_DIRECT),
            "vouch_relays": relays,
            "vouch_mask": row[F_VOUCH_MASK],
        })
    if (flags & FLAG_FD_ROUND) and sf("probed_by"):
        events.append({
            "kind": "probed",
            "tick": tick,
            "subject": tracer,
            "probes": sf("probed_by"),
            "missed": sf("probed_miss"),
            "missed_by": sf("probed_miss_by"),
        })
    if sf("new_suspect"):
        events.append({
            "kind": "suspect_raised",  # FD verdicts — the lineage origin
            "tick": tick,
            "subject": tracer,
            "count": sf("new_suspect"),
            "observer": sf("new_suspect_by"),
        })
    if sf("new_dead"):
        events.append({
            "kind": "dead",  # suspicion-expiry transitions this tick
            "tick": tick,
            "subject": tracer,
            "count": sf("new_dead"),
            "observer": sf("new_dead_by"),
        })
    if flags & FLAG_SELF_REFUTED:
        events.append({"kind": "refute", "tick": tick, "subject": tracer})
    if flags & FLAG_SYNC_DUE:
        events.append({
            "kind": "sync",
            "tick": tick,
            "observer": tracer,
            "peer": row[spec.sync_field("sync_peer")],
            "ok": bool(flags & FLAG_SYNC_OK),
            "req_accepts": row[spec.sync_field("sync_req_accepts")],
            "ack_accepts": row[spec.sync_field("sync_ack_accepts")],
        })
    for t, slot in enumerate(spec.rumor_slots):
        n_new = row[spec.rumor_field(t, "rumor_new_inf")]
        if n_new and tracer == spec.tracer_rows[0]:
            # rumor groups are replicated across every tracer's row (the
            # capture is slot-scoped, not tracer-scoped); decode them once
            events.append({
                "kind": "rumor_infection",
                "tick": tick,
                "slot": slot,
                "count": n_new,
                "node": row[spec.rumor_field(t, "rumor_inf_node")],
                "src": row[spec.rumor_field(t, "rumor_inf_src")],
            })
    return events


def decode_records(rows, spec: TraceSpec) -> List[Dict]:
    """Decode a [M, n_fields] block (oldest first) into a flat, tick-ordered
    event list. Rows whose tick is 0 are ring cells never written."""
    events: List[Dict] = []
    for row in rows:
        if int(row[F_TICK]) <= 0:
            continue
        events.extend(decode_record(row, spec))
    events.sort(key=lambda e: (e["tick"], e["kind"]))
    return events
