"""Topology-aware adjacency generators (r13) — circulant chord sets.

Every supported overlay is a CIRCULANT graph: node ``i``'s neighbors are
``(i + c) mod N`` for a static python chord set ``c in chords(spec, N)``.
That representation is the whole design: adjacency is a closed-form
function of (row, chord), so

* no engine ever materializes an [N, N] adjacency plane (the pview
  O(N·k) guarantee and its ``forbid_wide_values`` audit contract hold
  unchanged — selection is O(N·fanout) integer math),
* the chord set is embedded in the traced program as a tiny [C] constant
  (static per spec, like every other protocol knob), and
* the scalar oracles mirror selection with the same integer arithmetic.

Chord sets are ASCENDING: the ``accelerated`` strategy walks them in
order (the doubling schedule), so order is part of the contract.
"""

from __future__ import annotations

import functools


def _ceil_log2(n: int) -> int:
    return int(n - 1).bit_length() if n > 1 else 0


def auto_torus_rows(n: int) -> int:
    """Largest divisor of ``n`` at or below sqrt(n) (>= 2)."""
    r = int(n**0.5)
    while r > 1 and n % r:
        r -= 1
    return r


def torus_dims(spec, n: int) -> tuple:
    rows = spec.torus_rows or auto_torus_rows(n)
    if rows < 2 or n % rows or n // rows < 2:
        raise ValueError(
            f"torus needs rows >= 2 dividing N with cols >= 2: rows={rows}, "
            f"N={n} (set DissemSpec.torus_rows to a proper divisor)"
        )
    return rows, n // rows


def zone_size(spec, n: int) -> int:
    z = spec.geo_zones
    if n % z or n // z < 4:
        raise ValueError(
            f"geo topology needs geo_zones ({z}) dividing N ({n}) with at "
            "least 4 members per zone"
        )
    return n // z


def zone_of(spec, n: int, i):
    """Zone index of row(s) ``i`` (works on ints and arrays)."""
    return i // zone_size(spec, n)


def _doubling_chords(n: int, cap: int, odd: bool) -> list:
    """Ascending geometric chords below ``n``: the doubling chain that
    makes deterministic dissemination cover an interval of size 2^C in C
    steps. ``odd=True`` forces every chord past 1 to be odd ((2^j)|1) so
    the set never traps a parity class when used alone (the pview warm-
    overlay lesson); the plain powers-of-two set always contains chord 1,
    which already generates all residues."""
    out = [1]
    j = 1
    while len(out) < cap:
        c = (1 << j) | 1 if odd else (1 << j)
        if c >= n:
            break
        if c not in out:
            out.append(c)
        j += 1
    return out


@functools.lru_cache(maxsize=None)
def _chords_cached(spec, n: int) -> tuple:
    if n < 4:
        raise ValueError(f"structured topologies need N >= 4 (got {n})")
    topo = spec.topology
    if topo == "ring":
        return (1, n - 1)
    if topo == "torus":
        rows, cols = torus_dims(spec, n)
        # {±1, ±cols} — the 4-neighbor wrap; dedup keeps N=4-ish corners sane
        return tuple(dict.fromkeys((1, cols, n - cols, n - 1)))
    if topo == "expander":
        cap = spec.degree or max(2, _ceil_log2(n))
        return tuple(_doubling_chords(n, cap, odd=True))
    if topo == "geo":
        zs = zone_size(spec, n)
        cap = spec.degree or max(2, _ceil_log2(zs))
        local = _doubling_chords(zs, cap, odd=True)
        # the WAN chord: the same slot of the NEXT zone — zones form a
        # delay ring; ascending order puts it last, so the accelerated
        # schedule fills the zone before hopping
        return tuple(local + [zs])
    # full + a deterministic strategy: the virtual-hypercube doubling set
    return tuple(_doubling_chords(n, max(2, _ceil_log2(n)), odd=False))


def chords(spec, n: int) -> tuple:
    """The spec's static chord set for capacity ``n`` (python ints,
    ascending). ``full`` + a uniform strategy has no chord set (the engine
    sampler is used); asking for one is a caller bug."""
    if spec.uniform_selection:
        raise ValueError(
            "uniform selection (push/push_pull on 'full') has no chord set"
        )
    return _chords_cached(spec, n)


def connectivity_ok(spec, n: int) -> bool:
    """Chord set generates Z_n (the overlay is connected): gcd check."""
    import math

    g = n
    for c in chords(spec, n):
        g = math.gcd(g, c)
    return g == 1


def apply_geo_wan_delay(state, spec, ops, n: int):
    """Host-side WAN delay rings for the ``geo`` topology (dense engine):
    every cross-zone directed link gets the spec's mean delay (in ticks)
    through the engine's ``set_link_delay`` mutator. Requires
    ``params.delay_slots > 0``; called between ticks like every other
    link mutation. O(Z^2) block mutations — arm-time cost, not tick cost."""
    if spec.topology != "geo" or spec.geo_wan_delay_ticks <= 0:
        return state
    zs = zone_size(spec, n)
    zones = [list(range(z * zs, (z + 1) * zs)) for z in range(spec.geo_zones)]
    for a in range(len(zones)):
        for b in range(len(zones)):
            if a != b:
                state = ops.set_link_delay(
                    state, zones[a], zones[b], float(spec.geo_wan_delay_ticks)
                )
    return state
