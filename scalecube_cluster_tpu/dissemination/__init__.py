"""Dissemination strategy zoo (r13): pluggable gossip strategies,
topology-aware circulant adjacency, and certified spread-time curves.

See :mod:`.spec` for the strategy/topology catalog, :mod:`.strategies`
for the engine seam, :mod:`.topology` for the chord generators, and
:mod:`.certify` for the theory-vs-measured certification harness
(``spread_certifier``). docs/DISSEMINATION.md is the narrative."""

from .spec import DEFAULT, STRATEGIES, TOPOLOGIES, DissemSpec  # noqa: F401
from . import strategies, topology  # noqa: F401


def __getattr__(name):
    # certify pulls in the engines; keep the package import light for the
    # params modules that only need the spec
    if name in ("certify", "spread_certifier", "measure_spread", "theory_bound",
                "certify_spread_mc", "fp_rate_mc", "mc_spread_certifier",
                "MC_MIN_SAMPLES"):
        from . import certify as _c

        if name == "certify":
            return _c
        return getattr(_c, name)
    raise AttributeError(name)
