"""Spread-time certification harness (r13): theory vs measured curves.

For every (strategy x topology) the harness measures the rumor spread-time
distribution — inject one user rumor into a warm, loss-free cluster and
count ticks until EVERY up member is infected, across seeds — and checks
the worst measured time against a closed-form bound derived from the
cited result with explicit engineering constants:

==============  ==========  =======================================  ==========================
strategy        topology    bound (ticks; L=ceil_log2 N, F=fanout)   source of the asymptotic
==============  ==========  =======================================  ==========================
push            full        3L + 8                                   Pittel '87 (log2 N + ln N + o(log N)); via arXiv:1311.2839 §1
push_pull       full        3L + 8 (and <= push's measured median)   Karp et al. FOCS'00 push-pull O(log N); via arXiv:1504.03277 §1
push            expander    4L + 8                                   conductance-bounded spreading (arXiv:1311.2839 refs)
push_pull       expander    4L + 8                                   same
push            ring        N  (and >= (N/2)/(2F): certified LINEAR) wavefront diameter argument (the comparative baseline)
push            torus       3(r + c) + 8                             2-D wavefront diameter
push            geo         4*ceil_log2(zs) + 2Z(1+W) + 16           intra-zone spreading + Z WAN hops of delay W
accelerated     any         deterministic schedule bound, below      doubling-chord schedule (arXiv:1311.2839 randomness-efficient spreading; structure-exploiting iteration in the spirit of arXiv:1805.08531)
pipelined       any         accelerated bound * ceil(R/B) + R + 8    budget-rotation stretch; steady-state rate per arXiv:1504.03277
==============  ==========  =======================================  ==========================

Deterministic-schedule bound D(T): ring ceil(N / min(F, 2)) + 4 (each
tick extends the interval by one per scheduled direction); torus
ceil(4 / min(F, 4)) * (r + c) + 8; doubling chord sets (full / expander
/ geo-local) 4 * ceil(C / F) + 8 — two full rotations apply the
ascending chords in order from any cyclic start, doubling the infected
interval per chord; geo adds Z * (1 + W) + 8 for the inter-zone ring.

These are ENGINEERING bounds: the asymptotic shape comes from the cited
theory, the constants are chosen with explicit safety margin and are
part of the recorded artifact — a regression that breaks a strategy's
scaling class (say, turns expander push linear) fails the check long
before the constant matters. Measurements run the FULL SWIM tick (FD,
suspicion, SYNC all live) at zero link loss, so the curve is the
strategy's, not an idealization's: user rumors spread ONLY through the
gossip phase (SYNC anti-entropy carries membership records, not rumor
infections), which is exactly why the spread time isolates the
dissemination strategy.

``spread_certifier`` is the chaos/telemetry-facing entry point: it runs
a matrix of specs, optionally publishing per-entry certification events
onto a telemetry bus, and returns the artifact record
``benchmarks/config12_strategies.py`` writes to STRATEGY_BENCH_r13.json.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from . import topology as topo
from .spec import DissemSpec

# ONE ceil_log2 spelling with the topology generators (true ceiling —
# ceil_log2(256) = 8): the bound formulas and the chord-set caps must
# agree on what "log2 N" means or the recorded formula strings lie
_ceil_log2 = topo._ceil_log2


def det_schedule_bound(spec: DissemSpec, n: int, fanout: int) -> int:
    """Deterministic rotation bound D(T) for the accelerated schedule."""
    if spec.topology == "ring":
        return -(-n // min(fanout, 2)) + 4
    if spec.topology == "torus":
        r, c = topo.torus_dims(spec, n)
        return -(-4 // min(fanout, 4)) * (r + c) + 8
    ch = topo.chords(spec, n)
    base = 4 * -(-len(ch) // fanout) + 8
    if spec.topology == "geo":
        base += spec.geo_zones * (1 + spec.geo_wan_delay_ticks) + 8
    return base


def theory_bound(
    spec: DissemSpec, n: int, fanout: int, rumor_slots: int = 8
) -> dict:
    """Closed-form spread-time bound for one (strategy, topology) at size
    ``n`` — see the module-docstring table. Returns ``{bound_ticks,
    lower_bound_ticks, formula, citation}`` (``lower_bound_ticks`` is 0
    except where the certification also asserts slowness — the ring's
    linear-diameter class)."""
    L = _ceil_log2(n)
    s, t = spec.strategy, spec.topology
    lower = 0
    if s == "accelerated":
        bound = det_schedule_bound(spec, n, fanout)
        formula = "det_schedule_bound(T)"
        citation = "arXiv:1311.2839 (doubling schedule); arXiv:1805.08531 (structure-exploiting iteration)"
    elif s == "tuneable":
        # the mixed walk covers the deterministic rotation in expected
        # 1/mix rotations; the randomized complement spreads push-like on
        # the same chords — take the stretched deterministic bound plus
        # the randomized log term as a (generous, certifiable) ceiling
        mix = max(float(spec.tuneable_mix), 0.1)
        bound = int(round(det_schedule_bound(spec, n, fanout) / mix)) + 3 * L + 8
        formula = f"det_schedule_bound(T)/max(mix,0.1)={mix:g} + 3*ceil_log2(N) + 8"
        citation = "arXiv:1506.02288 (robust and tuneable gossiping family)"
    elif s == "pipelined":
        stretch = -(-rumor_slots // min(spec.pipeline_budget, rumor_slots))
        bound = det_schedule_bound(spec, n, fanout) * stretch + rumor_slots + 8
        formula = f"det_schedule_bound(T) * ceil(R/B)={stretch} + R + 8"
        citation = "arXiv:1504.03277 (pipelined gossiping)"
    elif t in ("full", "expander"):
        c = 3 if t == "full" else 4
        bound = c * L + 8
        formula = f"{c}*ceil_log2(N) + 8"
        citation = (
            "Pittel '87 via arXiv:1311.2839"
            if t == "full"
            else "conductance-bounded spreading, arXiv:1311.2839 refs"
        )
        if s == "push_pull":
            citation = "Karp et al. FOCS'00 push-pull; " + citation
    elif t == "ring":
        bound = n
        lower = (n // 2) // (2 * fanout)
        formula = "N (upper); (N/2)/(2F) (lower: certified linear)"
        citation = "wavefront diameter argument"
    elif t == "torus":
        r, c = topo.torus_dims(spec, n)
        bound = 3 * (r + c) + 8
        formula = "3*(rows + cols) + 8"
        citation = "2-D wavefront diameter"
    else:  # geo
        zs = topo.zone_size(spec, n)
        Z, W = spec.geo_zones, spec.geo_wan_delay_ticks
        bound = 4 * _ceil_log2(zs) + 2 * Z * (1 + W) + 16
        formula = "4*ceil_log2(zone) + 2*Z*(1+W) + 16"
        citation = "intra-zone spreading + inter-zone delay ring"
    return {
        "bound_ticks": int(bound),
        "lower_bound_ticks": int(lower),
        "formula": formula,
        "citation": citation,
    }


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------


def _dense_runner(spec: DissemSpec, n: int, fanout: int, rumor_slots: int,
                  window: int):
    import jax

    from ..ops import state as S
    from ..ops.kernel import make_run
    from ..ops.state import SimParams

    delay_slots = 0
    if spec.topology == "geo" and spec.geo_wan_delay_ticks > 0:
        delay_slots = min(2 * spec.geo_wan_delay_ticks + 2, 8)
    params = SimParams(
        capacity=n, fanout=fanout, repeat_mult=3, ping_req_k=2, fd_every=5,
        sync_every=64, suspicion_mult=5, rumor_slots=rumor_slots,
        seed_rows=(0,), full_metrics=False, dissem=spec,
        delay_slots=delay_slots,
    )
    step = make_run(params, window)

    def fresh(origin: int):
        st = S.init_state(params, n, warm=True)
        st = topo.apply_geo_wan_delay(st, spec, S, n)
        return S.spread_rumor(st, 0, origin=origin)

    def inject(st, slot: int, origin: int):
        return S.spread_rumor(st, slot, origin=origin)

    return params, step, fresh, inject, jax


def _pview_runner(spec: DissemSpec, n: int, fanout: int, rumor_slots: int,
                  window: int):
    import jax

    import scalecube_cluster_tpu.ops.pview as PV

    if spec.topology == "geo" and spec.geo_wan_delay_ticks > 0:
        raise ValueError(
            "the pview engine has no per-link delay plane — certify geo "
            "WAN delay on the dense engine"
        )
    params = PV.PviewParams(
        capacity=n, fanout=fanout, repeat_mult=3, ping_req_k=2, fd_every=5,
        sync_every=64, suspicion_mult=5, rumor_slots=rumor_slots,
        seed_rows=(0,), dissem=spec,
    )
    step = PV.make_pview_run(params, window)

    def fresh(origin: int):
        st = PV.init_pview_state(params, n, warm=True)
        return PV.spread_rumor(st, 0, origin=origin)

    def inject(st, slot: int, origin: int):
        return PV.spread_rumor(st, slot, origin=origin)

    return params, step, fresh, inject, jax


_RUNNERS = {"dense": _dense_runner, "pview": _pview_runner}


def measure_spread(
    spec: DissemSpec,
    n: int = 256,
    engine: str = "dense",
    seeds: Sequence[int] = (0, 1, 2, 3, 4),
    fanout: int = 3,
    rumor_slots: int = 8,
    max_ticks: Optional[int] = None,
    window: int = 32,
) -> dict:
    """Measure the single-rumor spread-time distribution of one spec:
    ticks from injection to 100% up-member coverage, per seed (seed
    varies both the origin row and the PRNG chain). Returns the raw
    measurement record; ``None`` in ``spread_ticks`` marks a seed that
    never reached full coverage within ``max_ticks``."""
    bound = theory_bound(spec, n, fanout, rumor_slots)
    if max_ticks is None:
        max_ticks = 4 * bound["bound_ticks"] + 4 * window
    params, step, fresh, _inject, jax = _RUNNERS[engine](
        spec, n, fanout, rumor_slots, window
    )
    ticks: list = []
    curves: list = []
    for seed in seeds:
        st = fresh(origin=(seed * 37 + 1) % n)
        key = jax.random.PRNGKey(1000 + seed)
        cov_curve: list = []
        hit = None
        for w0 in range(0, max_ticks, window):
            st, key, ms, _w = step(st, key)
            cov = np.asarray(ms["rumor_coverage"])[:, 0]
            cov_curve.extend(float(c) for c in cov)
            full = np.nonzero(cov >= 1.0)[0]
            if full.size:
                hit = w0 + int(full[0]) + 1
                break
        ticks.append(hit)
        if len(cov_curve) > 512:  # artifact size: stride long curves
            stride = -(-len(cov_curve) // 512)
            cov_curve = cov_curve[::stride]
        curves.append([round(c, 4) for c in cov_curve])
    del step  # drop the compiled window before the next spec compiles
    good = [t for t in ticks if t is not None]
    return {
        "strategy": spec.strategy,
        "topology": spec.topology,
        "engine": engine,
        "n": n,
        "fanout": fanout,
        "rumor_slots": rumor_slots,
        "seeds": list(seeds),
        "spread_ticks": ticks,
        "spread_ticks_median": float(np.median(good)) if good else None,
        "spread_ticks_max": max(good) if good else None,
        "coverage_curves": curves,
        **{k: v for k, v in bound.items()},
    }


def certify_spread(record: dict) -> dict:
    """Fold the bound check into a measurement record: every seed must
    reach full coverage, the worst seed must beat ``bound_ticks``, and a
    nonzero ``lower_bound_ticks`` (the ring's linear class) must also be
    EXCEEDED by the best seed — certifying the topology is genuinely
    slow, which is the curve's comparative content."""
    ticks = record["spread_ticks"]
    ok = all(t is not None for t in ticks)
    if ok:
        ok = max(ticks) <= record["bound_ticks"]
        if record["lower_bound_ticks"]:
            ok = ok and min(ticks) >= record["lower_bound_ticks"]
    return {**record, "certified": bool(ok)}


def measure_pipeline_steady_state(
    spec: DissemSpec,
    n: int = 256,
    n_rumors: int = 4,
    seeds: Sequence[int] = (0,),
    fanout: int = 3,
    rumor_slots: int = 8,
    window: int = 32,
) -> dict:
    """The pipelined strategy's multi-rumor claim (arXiv:1504.03277):
    ``n_rumors`` rumors injected TOGETHER must each individually meet the
    stretched single-rumor bound — concurrent rumors share the budget
    rotation as a pipeline instead of multiplying each other's completion
    time. Records per-rumor completions + the pipelining overhead (last
    vs first completion)."""
    assert spec.strategy == "pipelined"
    bound = theory_bound(spec, n, fanout, rumor_slots)["bound_ticks"]
    max_ticks = 4 * bound + 4 * window
    params, step, fresh, inject, jax = _RUNNERS["dense"](
        spec, n, fanout, rumor_slots, window
    )
    runs = []
    for seed in seeds:
        st = fresh(origin=(seed * 37 + 1) % n)
        for k in range(1, n_rumors):
            st = inject(st, k, origin=(seed * 37 + 1 + k * 11) % n)
        key = jax.random.PRNGKey(2000 + seed)
        done = [None] * n_rumors
        for w0 in range(0, max_ticks, window):
            st, key, ms, _w = step(st, key)
            cov = np.asarray(ms["rumor_coverage"])[:, :n_rumors]
            for k in range(n_rumors):
                if done[k] is None:
                    full = np.nonzero(cov[:, k] >= 1.0)[0]
                    if full.size:
                        done[k] = w0 + int(full[0]) + 1
            if all(d is not None for d in done):
                break
        runs.append(done)
    del step
    flat = [d for run in runs for d in run]
    ok = all(d is not None and d <= bound for d in flat)
    return {
        "strategy": spec.strategy,
        "topology": spec.topology,
        "n": n,
        "n_rumors": n_rumors,
        "completions": runs,
        "single_rumor_bound_ticks": bound,
        "pipelining_overhead_ticks": (
            max(d for d in flat) - min(d for d in flat)
            if flat and all(d is not None for d in flat)
            else None
        ),
        "certified": bool(ok),
    }


# ---------------------------------------------------------------------------
# the chaos/telemetry-facing entry point
# ---------------------------------------------------------------------------

#: the default certification matrix (>= 3 strategies x >= 3 topologies,
#: the r13 acceptance floor, plus the comparative extras)
DEFAULT_MATRIX = (
    ("push", "full", "dense"),
    ("push", "ring", "dense"),
    ("push", "torus", "dense"),
    ("push", "expander", "dense"),
    ("push", "geo", "dense"),
    ("push_pull", "full", "dense"),
    ("push_pull", "expander", "dense"),
    ("pipelined", "ring", "dense"),
    ("pipelined", "expander", "dense"),
    ("pipelined", "full", "dense"),
    ("accelerated", "ring", "dense"),
    ("accelerated", "torus", "dense"),
    ("accelerated", "expander", "dense"),
    ("push", "expander", "pview"),
    ("accelerated", "expander", "pview"),
    # r14 fifth strategy (ROADMAP item-3 leftover): the robust/tuneable
    # family, certified on the expander (and the ring's linear class is
    # already pinned by the pure strategies above)
    ("tuneable", "expander", "dense"),
    ("tuneable", "full", "dense"),
)


def spread_certifier(
    matrix=None,
    n: int = 256,
    seeds: Sequence[int] = (0, 1, 2, 3, 4),
    fanout: int = 3,
    rumor_slots: int = 8,
    geo_wan_delay_ticks: int = 2,
    pipeline_budget: int = 2,
    bus=None,
    log=None,
) -> dict:
    """Run the certification matrix and return the r13 artifact record.

    ``bus`` (a ``telemetry.TelemetryBus``) receives one
    ``spread_certified`` event per entry — the chaos/telemetry
    integration: a certification sweep armed next to a live driver's
    plane leaves its verdicts on the same ordered stream the scenario
    events ride. ``log`` is an optional ``print``-like progress sink."""
    entries = []
    matrix = tuple(matrix or DEFAULT_MATRIX)
    for strat, topol, engine in matrix:
        spec = DissemSpec(
            strategy=strat,
            topology=topol,
            geo_wan_delay_ticks=geo_wan_delay_ticks if topol == "geo" else 0,
            pipeline_budget=pipeline_budget,
        )
        rec = certify_spread(
            measure_spread(
                spec, n=n, engine=engine, seeds=seeds, fanout=fanout,
                rumor_slots=rumor_slots,
            )
        )
        entries.append(rec)
        if log:
            log(
                f"{engine}/{strat}/{topol}: spread {rec['spread_ticks']} "
                f"<= bound {rec['bound_ticks']} "
                f"{'OK' if rec['certified'] else 'VIOLATION'}"
            )
        if bus is not None:
            bus.publish(
                "dissemination", "spread_certified",
                strategy=strat, topology=topol, engine=engine,
                certified=rec["certified"],
                spread_ticks_max=rec["spread_ticks_max"],
                bound_ticks=rec["bound_ticks"],
            )
    # the steady-state claim belongs to the pipelined strategy: it runs
    # (and gates the verdict) only when the matrix certifies pipelined —
    # a single-combo run of another strategy must not pay for it nor
    # fail on it
    pipeline = None
    if any(strat == "pipelined" for strat, _t, _e in matrix):
        pipeline = measure_pipeline_steady_state(
            DissemSpec(strategy="pipelined", topology="expander",
                       pipeline_budget=pipeline_budget),
            n=n, seeds=tuple(seeds)[:1], fanout=fanout,
            rumor_slots=rumor_slots,
        )
        if log:
            log(
                f"pipelined steady-state: completions "
                f"{pipeline['completions']} "
                f"<= {pipeline['single_rumor_bound_ticks']} "
                f"{'OK' if pipeline['certified'] else 'VIOLATION'}"
            )
        if bus is not None:
            bus.publish(
                "dissemination", "pipeline_steady_state",
                certified=pipeline["certified"],
                overhead=pipeline["pipelining_overhead_ticks"],
            )
    strategies = sorted({e["strategy"] for e in entries if e["certified"]})
    topologies = sorted({e["topology"] for e in entries if e["certified"]})
    return {
        "n": n,
        "seeds": list(seeds),
        "fanout": fanout,
        "rumor_slots": rumor_slots,
        "entries": entries,
        "pipeline_steady_state": pipeline,
        "certified_strategies": strategies,
        "certified_topologies": topologies,
        "n_certified": sum(1 for e in entries if e["certified"]),
        "n_entries": len(entries),
        "ok": all(e["certified"] for e in entries)
        and (pipeline is None or pipeline["certified"]),
    }
