"""Spread-time certification harness (r13): theory vs measured curves.

For every (strategy x topology) the harness measures the rumor spread-time
distribution — inject one user rumor into a warm, loss-free cluster and
count ticks until EVERY up member is infected, across seeds — and checks
the worst measured time against a closed-form bound derived from the
cited result with explicit engineering constants:

==============  ==========  =======================================  ==========================
strategy        topology    bound (ticks; L=ceil_log2 N, F=fanout)   source of the asymptotic
==============  ==========  =======================================  ==========================
push            full        3L + 8                                   Pittel '87 (log2 N + ln N + o(log N)); via arXiv:1311.2839 §1
push_pull       full        3L + 8 (and <= push's measured median)   Karp et al. FOCS'00 push-pull O(log N); via arXiv:1504.03277 §1
push            expander    4L + 8                                   conductance-bounded spreading (arXiv:1311.2839 refs)
push_pull       expander    4L + 8                                   same
push            ring        N  (and >= (N/2)/(2F): certified LINEAR) wavefront diameter argument (the comparative baseline)
push            torus       3(r + c) + 8                             2-D wavefront diameter
push            geo         4*ceil_log2(zs) + 2Z(1+W) + 16           intra-zone spreading + Z WAN hops of delay W
accelerated     any         deterministic schedule bound, below      doubling-chord schedule (arXiv:1311.2839 randomness-efficient spreading; structure-exploiting iteration in the spirit of arXiv:1805.08531)
pipelined       any         accelerated bound * ceil(R/B) + R + 8    budget-rotation stretch; steady-state rate per arXiv:1504.03277
==============  ==========  =======================================  ==========================

Deterministic-schedule bound D(T): ring ceil(N / min(F, 2)) + 4 (each
tick extends the interval by one per scheduled direction); torus
ceil(4 / min(F, 4)) * (r + c) + 8; doubling chord sets (full / expander
/ geo-local) 4 * ceil(C / F) + 8 — two full rotations apply the
ascending chords in order from any cyclic start, doubling the infected
interval per chord; geo adds Z * (1 + W) + 8 for the inter-zone ring.

These are ENGINEERING bounds: the asymptotic shape comes from the cited
theory, the constants are chosen with explicit safety margin and are
part of the recorded artifact — a regression that breaks a strategy's
scaling class (say, turns expander push linear) fails the check long
before the constant matters. Measurements run the FULL SWIM tick (FD,
suspicion, SYNC all live) at zero link loss, so the curve is the
strategy's, not an idealization's: user rumors spread ONLY through the
gossip phase (SYNC anti-entropy carries membership records, not rumor
infections), which is exactly why the spread time isolates the
dissemination strategy.

``spread_certifier`` is the chaos/telemetry-facing entry point: it runs
a matrix of specs, optionally publishing per-entry certification events
onto a telemetry bus, and returns the artifact record
``benchmarks/config12_strategies.py`` writes to STRATEGY_BENCH_r13.json.

**Monte Carlo certification (r15).** The serial harness above draws its
verdict from a handful of seeds run one window-dispatch at a time — an
engineering SPOT CHECK, and labeled as such in every artifact
(``verdict_kind: "spot-check"`` whenever ``sample_size <
theory_bound()["mc_min_samples"]``). The fleet engine
(:mod:`..ops.fleet`) turns the same measurement into a statistical one:
:func:`certify_spread_mc` vmaps the cell's window over ≥1000 scenarios
(one rumor per scenario, per-scenario origin + PRNG chain), folds
ticks-to-coverage ON DEVICE across windows (one [S] readback per cell,
never per seed), and reports REAL confidence intervals —

* a **Wilson score interval** on ``P(spread_ticks <= bound_ticks)``:
  with ``k`` of ``S`` seeds inside the bound and ``p̂ = k/S``,
  ``(p̂ + z²/2S ± z·sqrt(p̂(1-p̂)/S + z²/4S²)) / (1 + z²/S)``;
* **distribution-free order-statistic CIs** on the median and p99
  spread-time quantiles: the q-quantile's CI is the pair of order
  statistics at ranks ``S·q ± z·sqrt(S·q(1-q))`` (the binomial rank
  bracket, normal-approximated — exact to <1 rank at the S ≥ 1000
  sample sizes this service runs).

A cell certifies when every seed finished, the p99 CI's UPPER endpoint
sits inside the theory bound, the Wilson LOWER bound on
``P(within bound)`` is ≥ 0.99, and (for the ring's linear class) the p01
CI's LOWER endpoint exceeds the linear lower bound.
:func:`fp_rate_mc` is the chaos twin: the r14 false-positive sentinel's
check, vmapped over a fleet driven through a loss-adversarial scenario
by the batched ``StateTimeline`` fold, with a Wilson interval on the
per-scenario false-DEAD rate. ``mc_spread_certifier`` runs the MC matrix
for ``benchmarks/config14_fleet.py`` → FLEET_BENCH_r15.json.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np

#: minimum seeds for a verdict to count as Monte Carlo rather than a
#: spot check — every bound record carries it (``mc_min_samples``) so
#: artifacts can never silently mix single-seed and MC verdicts
MC_MIN_SAMPLES = 1000

from . import topology as topo
from .spec import DissemSpec

# ONE ceil_log2 spelling with the topology generators (true ceiling —
# ceil_log2(256) = 8): the bound formulas and the chord-set caps must
# agree on what "log2 N" means or the recorded formula strings lie
_ceil_log2 = topo._ceil_log2


def det_schedule_bound(spec: DissemSpec, n: int, fanout: int) -> int:
    """Deterministic rotation bound D(T) for the accelerated schedule."""
    if spec.topology == "ring":
        return -(-n // min(fanout, 2)) + 4
    if spec.topology == "torus":
        r, c = topo.torus_dims(spec, n)
        return -(-4 // min(fanout, 4)) * (r + c) + 8
    ch = topo.chords(spec, n)
    base = 4 * -(-len(ch) // fanout) + 8
    if spec.topology == "geo":
        base += spec.geo_zones * (1 + spec.geo_wan_delay_ticks) + 8
    return base


def theory_bound(
    spec: DissemSpec, n: int, fanout: int, rumor_slots: int = 8
) -> dict:
    """Closed-form spread-time bound for one (strategy, topology) at size
    ``n`` — see the module-docstring table. Returns ``{bound_ticks,
    lower_bound_ticks, formula, citation}`` (``lower_bound_ticks`` is 0
    except where the certification also asserts slowness — the ring's
    linear-diameter class)."""
    L = _ceil_log2(n)
    s, t = spec.strategy, spec.topology
    lower = 0
    if s == "accelerated":
        bound = det_schedule_bound(spec, n, fanout)
        formula = "det_schedule_bound(T)"
        citation = "arXiv:1311.2839 (doubling schedule); arXiv:1805.08531 (structure-exploiting iteration)"
    elif s == "tuneable":
        # the mixed walk covers the deterministic rotation in expected
        # 1/mix rotations; the randomized complement spreads push-like on
        # the same chords — take the stretched deterministic bound plus
        # the randomized log term as a (generous, certifiable) ceiling
        mix = max(float(spec.tuneable_mix), 0.1)
        bound = int(round(det_schedule_bound(spec, n, fanout) / mix)) + 3 * L + 8
        formula = f"det_schedule_bound(T)/max(mix,0.1)={mix:g} + 3*ceil_log2(N) + 8"
        citation = "arXiv:1506.02288 (robust and tuneable gossiping family)"
    elif s == "pipelined":
        stretch = -(-rumor_slots // min(spec.pipeline_budget, rumor_slots))
        bound = det_schedule_bound(spec, n, fanout) * stretch + rumor_slots + 8
        formula = f"det_schedule_bound(T) * ceil(R/B)={stretch} + R + 8"
        citation = "arXiv:1504.03277 (pipelined gossiping)"
    elif t in ("full", "expander"):
        c = 3 if t == "full" else 4
        bound = c * L + 8
        formula = f"{c}*ceil_log2(N) + 8"
        citation = (
            "Pittel '87 via arXiv:1311.2839"
            if t == "full"
            else "conductance-bounded spreading, arXiv:1311.2839 refs"
        )
        if s == "push_pull":
            citation = "Karp et al. FOCS'00 push-pull; " + citation
    elif t == "ring":
        bound = n
        lower = (n // 2) // (2 * fanout)
        formula = "N (upper); (N/2)/(2F) (lower: certified linear)"
        citation = "wavefront diameter argument"
    elif t == "torus":
        r, c = topo.torus_dims(spec, n)
        bound = 3 * (r + c) + 8
        formula = "3*(rows + cols) + 8"
        citation = "2-D wavefront diameter"
    else:  # geo
        zs = topo.zone_size(spec, n)
        Z, W = spec.geo_zones, spec.geo_wan_delay_ticks
        bound = 4 * _ceil_log2(zs) + 2 * Z * (1 + W) + 16
        formula = "4*ceil_log2(zone) + 2*Z*(1+W) + 16"
        citation = "intra-zone spreading + inter-zone delay ring"
    return {
        "bound_ticks": int(bound),
        "lower_bound_ticks": int(lower),
        "formula": formula,
        "citation": citation,
        # r15: the sample-size floor below which a verdict against this
        # bound is a SPOT CHECK, not a Monte Carlo certification — the
        # measurement records stamp verdict_kind from it, so the two
        # never mix silently in an artifact
        "mc_min_samples": MC_MIN_SAMPLES,
    }


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------


def _dense_setup(spec: DissemSpec, n: int, fanout: int, rumor_slots: int):
    """(params, base_state_fn, ops_module) for one dense certification
    cell — shared by the serial spot-check runner and the MC fleet
    service (same protocol knobs, same warm loss-free start)."""
    from ..ops import state as S
    from ..ops.state import SimParams

    delay_slots = 0
    if spec.topology == "geo" and spec.geo_wan_delay_ticks > 0:
        delay_slots = min(2 * spec.geo_wan_delay_ticks + 2, 8)
    params = SimParams(
        capacity=n, fanout=fanout, repeat_mult=3, ping_req_k=2, fd_every=5,
        sync_every=64, suspicion_mult=5, rumor_slots=rumor_slots,
        seed_rows=(0,), full_metrics=False, dissem=spec,
        delay_slots=delay_slots,
    )

    def base():
        st = S.init_state(params, n, warm=True)
        return topo.apply_geo_wan_delay(st, spec, S, n)

    return params, base, S


def _sparse_setup(spec: DissemSpec, n: int, fanout: int, rumor_slots: int):
    """(params, base_state_fn, ops_module) for one SPARSE certification
    cell (r16, ROADMAP 3a): the record-queue engine enters the MC matrix
    so the statistical load stops resting on the dense engine alone. Same
    protocol knobs as the dense cell; the lean scalar-loss layout (the
    spread measurement runs loss-free anyway)."""
    import scalecube_cluster_tpu.ops.sparse as SP

    if spec.topology == "geo" and spec.geo_wan_delay_ticks > 0:
        raise ValueError(
            "the lean sparse layout has no per-link delay plane — certify "
            "geo WAN delay on the dense engine"
        )
    params = SP.SparseParams(
        capacity=n, fanout=fanout, repeat_mult=3, ping_req_k=2, fd_every=5,
        sync_every=64, suspicion_mult=5, rumor_slots=rumor_slots,
        mr_slots=max(64, n * 4), announce_slots=max(32, n // 2),
        seed_rows=(0,), dissem=spec,
    )

    def base():
        return SP.init_sparse_state(params, n, warm=True)

    return params, base, SP


def _pview_setup(spec: DissemSpec, n: int, fanout: int, rumor_slots: int):
    import scalecube_cluster_tpu.ops.pview as PV

    if spec.topology == "geo" and spec.geo_wan_delay_ticks > 0:
        raise ValueError(
            "the pview engine has no per-link delay plane — certify geo "
            "WAN delay on the dense engine"
        )
    params = PV.PviewParams(
        capacity=n, fanout=fanout, repeat_mult=3, ping_req_k=2, fd_every=5,
        sync_every=64, suspicion_mult=5, rumor_slots=rumor_slots,
        seed_rows=(0,), dissem=spec,
    )

    def base():
        return PV.init_pview_state(params, n, warm=True)

    return params, base, PV


_SETUPS = {
    "dense": _dense_setup,
    "pview": _pview_setup,
    "sparse": _sparse_setup,
}


def _dense_runner(spec: DissemSpec, n: int, fanout: int, rumor_slots: int,
                  window: int):
    import jax

    from ..ops.kernel import make_run

    params, base, S = _dense_setup(spec, n, fanout, rumor_slots)
    step = make_run(params, window)

    def fresh(origin: int):
        return S.spread_rumor(base(), 0, origin=origin)

    def inject(st, slot: int, origin: int):
        return S.spread_rumor(st, slot, origin=origin)

    return params, step, fresh, inject, jax


def _pview_runner(spec: DissemSpec, n: int, fanout: int, rumor_slots: int,
                  window: int):
    import jax

    params, base, PV = _pview_setup(spec, n, fanout, rumor_slots)
    step = PV.make_pview_run(params, window)

    def fresh(origin: int):
        return PV.spread_rumor(base(), 0, origin=origin)

    def inject(st, slot: int, origin: int):
        return PV.spread_rumor(st, slot, origin=origin)

    return params, step, fresh, inject, jax


def _sparse_runner(spec: DissemSpec, n: int, fanout: int, rumor_slots: int,
                   window: int):
    import jax

    params, base, SP = _sparse_setup(spec, n, fanout, rumor_slots)
    step = SP.make_sparse_run(params, window)

    def fresh(origin: int):
        return SP.spread_rumor(base(), 0, origin=origin)

    def inject(st, slot: int, origin: int):
        return SP.spread_rumor(st, slot, origin=origin)

    return params, step, fresh, inject, jax


_RUNNERS = {
    "dense": _dense_runner,
    "pview": _pview_runner,
    "sparse": _sparse_runner,
}


def measure_spread(
    spec: DissemSpec,
    n: int = 256,
    engine: str = "dense",
    seeds: Sequence[int] = (0, 1, 2, 3, 4),
    fanout: int = 3,
    rumor_slots: int = 8,
    max_ticks: Optional[int] = None,
    window: int = 32,
) -> dict:
    """Measure the single-rumor spread-time distribution of one spec:
    ticks from injection to 100% up-member coverage, per seed (seed
    varies both the origin row and the PRNG chain). Returns the raw
    measurement record; ``None`` in ``spread_ticks`` marks a seed that
    never reached full coverage within ``max_ticks``."""
    bound = theory_bound(spec, n, fanout, rumor_slots)
    if max_ticks is None:
        max_ticks = 4 * bound["bound_ticks"] + 4 * window
    params, step, fresh, _inject, jax = _RUNNERS[engine](
        spec, n, fanout, rumor_slots, window
    )
    ticks: list = []
    curves: list = []
    for seed in seeds:
        st = fresh(origin=(seed * 37 + 1) % n)
        key = jax.random.PRNGKey(1000 + seed)
        cov_curve: list = []
        hit = None
        for w0 in range(0, max_ticks, window):
            st, key, ms, _w = step(st, key)
            cov = np.asarray(ms["rumor_coverage"])[:, 0]
            cov_curve.extend(float(c) for c in cov)
            full = np.nonzero(cov >= 1.0)[0]
            if full.size:
                hit = w0 + int(full[0]) + 1
                break
        ticks.append(hit)
        if len(cov_curve) > 512:  # artifact size: stride long curves
            stride = -(-len(cov_curve) // 512)
            cov_curve = cov_curve[::stride]
        curves.append([round(c, 4) for c in cov_curve])
    del step  # drop the compiled window before the next spec compiles
    good = [t for t in ticks if t is not None]
    return {
        "strategy": spec.strategy,
        "topology": spec.topology,
        "engine": engine,
        "n": n,
        "fanout": fanout,
        "rumor_slots": rumor_slots,
        "seeds": list(seeds),
        # r15: a handful of serial seeds is a spot check, never a Monte
        # Carlo verdict — the label travels with the record so artifacts
        # cannot mix the two silently (certify_spread_mc stamps
        # "monte-carlo" + real confidence intervals)
        "sample_size": len(seeds),
        "verdict_kind": (
            "spot-check" if len(seeds) < bound["mc_min_samples"]
            else "monte-carlo"
        ),
        "spread_ticks": ticks,
        "spread_ticks_median": float(np.median(good)) if good else None,
        "spread_ticks_max": max(good) if good else None,
        "coverage_curves": curves,
        **{k: v for k, v in bound.items()},
    }


def certify_spread(record: dict) -> dict:
    """Fold the bound check into a measurement record: every seed must
    reach full coverage, the worst seed must beat ``bound_ticks``, and a
    nonzero ``lower_bound_ticks`` (the ring's linear class) must also be
    EXCEEDED by the best seed — certifying the topology is genuinely
    slow, which is the curve's comparative content."""
    ticks = record["spread_ticks"]
    ok = all(t is not None for t in ticks)
    if ok:
        ok = max(ticks) <= record["bound_ticks"]
        if record["lower_bound_ticks"]:
            ok = ok and min(ticks) >= record["lower_bound_ticks"]
    return {**record, "certified": bool(ok)}


def measure_pipeline_steady_state(
    spec: DissemSpec,
    n: int = 256,
    n_rumors: int = 4,
    seeds: Sequence[int] = (0,),
    fanout: int = 3,
    rumor_slots: int = 8,
    window: int = 32,
) -> dict:
    """The pipelined strategy's multi-rumor claim (arXiv:1504.03277):
    ``n_rumors`` rumors injected TOGETHER must each individually meet the
    stretched single-rumor bound — concurrent rumors share the budget
    rotation as a pipeline instead of multiplying each other's completion
    time. Records per-rumor completions + the pipelining overhead (last
    vs first completion)."""
    assert spec.strategy == "pipelined"
    bound = theory_bound(spec, n, fanout, rumor_slots)["bound_ticks"]
    max_ticks = 4 * bound + 4 * window
    params, step, fresh, inject, jax = _RUNNERS["dense"](
        spec, n, fanout, rumor_slots, window
    )
    runs = []
    for seed in seeds:
        st = fresh(origin=(seed * 37 + 1) % n)
        for k in range(1, n_rumors):
            st = inject(st, k, origin=(seed * 37 + 1 + k * 11) % n)
        key = jax.random.PRNGKey(2000 + seed)
        done = [None] * n_rumors
        for w0 in range(0, max_ticks, window):
            st, key, ms, _w = step(st, key)
            cov = np.asarray(ms["rumor_coverage"])[:, :n_rumors]
            for k in range(n_rumors):
                if done[k] is None:
                    full = np.nonzero(cov[:, k] >= 1.0)[0]
                    if full.size:
                        done[k] = w0 + int(full[0]) + 1
            if all(d is not None for d in done):
                break
        runs.append(done)
    del step
    flat = [d for run in runs for d in run]
    ok = all(d is not None and d <= bound for d in flat)
    return {
        "strategy": spec.strategy,
        "topology": spec.topology,
        "n": n,
        "n_rumors": n_rumors,
        "sample_size": len(list(seeds)),
        "verdict_kind": (
            "spot-check" if len(list(seeds)) < MC_MIN_SAMPLES
            else "monte-carlo"
        ),
        "completions": runs,
        "single_rumor_bound_ticks": bound,
        "pipelining_overhead_ticks": (
            max(d for d in flat) - min(d for d in flat)
            if flat and all(d is not None for d in flat)
            else None
        ),
        "certified": bool(ok),
    }


# ---------------------------------------------------------------------------
# the chaos/telemetry-facing entry point
# ---------------------------------------------------------------------------

#: the default certification matrix (>= 3 strategies x >= 3 topologies,
#: the r13 acceptance floor, plus the comparative extras)
DEFAULT_MATRIX = (
    ("push", "full", "dense"),
    ("push", "ring", "dense"),
    ("push", "torus", "dense"),
    ("push", "expander", "dense"),
    ("push", "geo", "dense"),
    ("push_pull", "full", "dense"),
    ("push_pull", "expander", "dense"),
    ("pipelined", "ring", "dense"),
    ("pipelined", "expander", "dense"),
    ("pipelined", "full", "dense"),
    ("accelerated", "ring", "dense"),
    ("accelerated", "torus", "dense"),
    ("accelerated", "expander", "dense"),
    ("push", "expander", "pview"),
    ("accelerated", "expander", "pview"),
    # r14 fifth strategy (ROADMAP item-3 leftover): the robust/tuneable
    # family, certified on the expander (and the ring's linear class is
    # already pinned by the pure strategies above)
    ("tuneable", "expander", "dense"),
    ("tuneable", "full", "dense"),
)


def spread_certifier(
    matrix=None,
    n: int = 256,
    seeds: Sequence[int] = (0, 1, 2, 3, 4),
    fanout: int = 3,
    rumor_slots: int = 8,
    geo_wan_delay_ticks: int = 2,
    pipeline_budget: int = 2,
    bus=None,
    log=None,
) -> dict:
    """Run the certification matrix and return the r13 artifact record.

    ``bus`` (a ``telemetry.TelemetryBus``) receives one
    ``spread_certified`` event per entry — the chaos/telemetry
    integration: a certification sweep armed next to a live driver's
    plane leaves its verdicts on the same ordered stream the scenario
    events ride. ``log`` is an optional ``print``-like progress sink."""
    entries = []
    matrix = tuple(matrix or DEFAULT_MATRIX)
    for strat, topol, engine in matrix:
        spec = DissemSpec(
            strategy=strat,
            topology=topol,
            geo_wan_delay_ticks=geo_wan_delay_ticks if topol == "geo" else 0,
            pipeline_budget=pipeline_budget,
        )
        rec = certify_spread(
            measure_spread(
                spec, n=n, engine=engine, seeds=seeds, fanout=fanout,
                rumor_slots=rumor_slots,
            )
        )
        entries.append(rec)
        if log:
            log(
                f"{engine}/{strat}/{topol}: spread {rec['spread_ticks']} "
                f"<= bound {rec['bound_ticks']} "
                f"{'OK' if rec['certified'] else 'VIOLATION'}"
            )
        if bus is not None:
            bus.publish(
                "dissemination", "spread_certified",
                strategy=strat, topology=topol, engine=engine,
                certified=rec["certified"],
                spread_ticks_max=rec["spread_ticks_max"],
                bound_ticks=rec["bound_ticks"],
            )
    # the steady-state claim belongs to the pipelined strategy: it runs
    # (and gates the verdict) only when the matrix certifies pipelined —
    # a single-combo run of another strategy must not pay for it nor
    # fail on it
    pipeline = None
    if any(strat == "pipelined" for strat, _t, _e in matrix):
        pipeline = measure_pipeline_steady_state(
            DissemSpec(strategy="pipelined", topology="expander",
                       pipeline_budget=pipeline_budget),
            n=n, seeds=tuple(seeds)[:1], fanout=fanout,
            rumor_slots=rumor_slots,
        )
        if log:
            log(
                f"pipelined steady-state: completions "
                f"{pipeline['completions']} "
                f"<= {pipeline['single_rumor_bound_ticks']} "
                f"{'OK' if pipeline['certified'] else 'VIOLATION'}"
            )
        if bus is not None:
            bus.publish(
                "dissemination", "pipeline_steady_state",
                certified=pipeline["certified"],
                overhead=pipeline["pipelining_overhead_ticks"],
            )
    strategies = sorted({e["strategy"] for e in entries if e["certified"]})
    topologies = sorted({e["topology"] for e in entries if e["certified"]})
    return {
        "n": n,
        "seeds": list(seeds),
        "fanout": fanout,
        "rumor_slots": rumor_slots,
        "entries": entries,
        "pipeline_steady_state": pipeline,
        "certified_strategies": strategies,
        "certified_topologies": topologies,
        "n_certified": sum(1 for e in entries if e["certified"]),
        "n_entries": len(entries),
        "ok": all(e["certified"] for e in entries)
        and (pipeline is None or pipeline["certified"]),
    }


# ---------------------------------------------------------------------------
# Monte Carlo certification service (r15, fleet-backed)
# ---------------------------------------------------------------------------

def _z_for(conf: float) -> float:
    """Two-sided normal quantile for a confidence level — exact via the
    stdlib inverse CDF, so a non-standard ``conf`` yields intervals at
    the confidence the artifact claims (never a silent 95% fallback)."""
    if not 0.0 < conf < 1.0:
        raise ValueError(f"confidence must be in (0, 1), got {conf}")
    from statistics import NormalDist

    return NormalDist().inv_cdf(0.5 + conf / 2.0)


def wilson_interval(k: int, n: int, conf: float = 0.95) -> tuple:
    """Wilson score interval for a binomial proportion k/n — the interval
    every MC artifact records for P(spread <= bound) / P(false-DEAD > 0).
    Well-behaved at the boundaries (k=0 / k=n), unlike the Wald interval,
    which is why it is the recorded method."""
    if n <= 0:
        return 0.0, 1.0
    z = _z_for(conf)
    p = k / n
    denom = 1.0 + z * z / n
    center = (p + z * z / (2 * n)) / denom
    half = z * math.sqrt(p * (1 - p) / n + z * z / (4 * n * n)) / denom
    return max(0.0, center - half), min(1.0, center + half)


def quantile_ci(sorted_samples, q: float, conf: float = 0.95) -> tuple:
    """(point, (lo, hi)): the empirical q-quantile with a distribution-free
    order-statistic confidence interval — the CI endpoints are the order
    statistics at ranks ``n·q ± z·sqrt(n·q(1-q))`` (the binomial rank
    bracket, normal-approximated; exact to <1 rank at the MC sample sizes
    this service runs). ``sorted_samples`` must be ascending."""
    xs = np.asarray(sorted_samples)
    n = xs.shape[0]
    if n == 0:
        return None, (None, None)
    z = _z_for(conf)
    mu = n * q
    sd = math.sqrt(max(n * q * (1 - q), 0.0))
    point = float(xs[min(max(math.ceil(mu) - 1, 0), n - 1)])
    lo = int(np.clip(math.floor(mu - z * sd) - 1, 0, n - 1))
    hi = int(np.clip(math.ceil(mu + z * sd), 0, n - 1))
    return point, (float(xs[lo]), float(xs[hi]))


def certify_spread_mc(
    spec: DissemSpec,
    n: int = 64,
    n_seeds: int = MC_MIN_SAMPLES,
    engine: str = "dense",
    fanout: int = 3,
    rumor_slots: int = 8,
    window: int = 32,
    base_seed: int = 0,
    max_ticks: Optional[int] = None,
    conf: float = 0.95,
) -> dict:
    """Monte Carlo spread-time certification of one (strategy, topology)
    cell: ``n_seeds`` independent clusters advance in FLEET windows (one
    XLA dispatch per window for all scenarios — :mod:`..ops.fleet`), the
    per-scenario ticks-to-full-coverage fold stays on device across
    windows, and the single [S] readback at the end feeds the interval
    statistics (see the module docstring for the exact formulas). Seed
    ``s`` varies both the rumor origin row and the PRNG chain, exactly
    as the serial spot check's seeds do."""
    import jax
    import jax.numpy as jnp

    from ..ops import fleet as FL

    import dataclasses as _dc

    bound = theory_bound(spec, n, fanout, rumor_slots)
    if max_ticks is None:
        max_ticks = 4 * bound["bound_ticks"] + 4 * window
    params, base, ops_mod = _SETUPS[engine](spec, n, fanout, rumor_slots)
    if hasattr(params, "quiet_gates"):
        # the fleet profile (ops/fleet.py): drop the quiet-tick lax.conds
        # — under vmap they run both branches AND select; the ungated
        # program is value-identical and leaner
        params = _dc.replace(params, quiet_gates=False)
    step = FL.make_fleet_run(params, window)
    seeds = np.arange(n_seeds) + base_seed
    origins = (seeds * 37 + 1) % n
    fs = FL.fleet_broadcast(base(), n_seeds)
    fs = FL.fleet_inject_rumor(ops_mod, fs, 0, origins)
    keys = FL.fleet_keys(1000 + seeds)
    hit = jnp.full((n_seeds,), -1, jnp.int32)
    sharded = jax.device_count() > 1 and n_seeds % jax.device_count() == 0
    if sharded:
        # scenario-axis device parallelism (zero collectives — see
        # fleet_mesh); the fold accumulator rides the same mesh so the
        # whole per-window loop stays sharded end to end
        mesh = FL.fleet_mesh()
        fs = FL.shard_fleet(fs, mesh)
        keys = FL.shard_fleet(keys, mesh)
        hit = FL.shard_fleet(hit, mesh)
    fold = jax.jit(FL.fold_first_full_coverage)
    windows = 0
    for w0 in range(0, max_ticks, window):
        fs, keys, ms, _w = step(fs, keys)
        hit = fold(hit, ms["rumor_coverage"][:, :, 0], w0)
        windows += 1
        # one SCALAR sync per window (bounded by windows, never by seeds)
        if bool((hit >= 0).all()):
            break
    del step
    ticks = np.asarray(hit)  # THE per-cell [S] readback
    finished = int((ticks >= 0).sum())
    good = np.sort(ticks[ticks >= 0])
    within = int(((ticks >= 0) & (ticks <= bound["bound_ticks"])).sum())
    wil = wilson_interval(within, n_seeds, conf)
    med, med_ci = quantile_ci(good, 0.5, conf)
    p99, p99_ci = quantile_ci(good, 0.99, conf)
    p01, p01_ci = quantile_ci(good, 0.01, conf)
    certified = (
        finished == n_seeds
        and p99_ci[1] is not None
        and p99_ci[1] <= bound["bound_ticks"]
        and wil[0] >= 0.99
    )
    if bound["lower_bound_ticks"]:
        # the ring's linear class: even the FAST tail must exceed the
        # linear lower bound (the comparative "genuinely slow" claim)
        certified = certified and (
            p01_ci[0] is not None
            and p01_ci[0] >= bound["lower_bound_ticks"]
        )
    hist = {}
    if good.size:
        vals, counts = np.unique(good, return_counts=True)
        hist = {int(v): int(c) for v, c in zip(vals, counts)}
    return {
        "strategy": spec.strategy,
        "topology": spec.topology,
        "engine": engine,
        "n": n,
        "fanout": fanout,
        "rumor_slots": rumor_slots,
        "n_seeds": n_seeds,
        "sample_size": n_seeds,
        "base_seed": base_seed,
        "verdict_kind": (
            "monte-carlo" if n_seeds >= MC_MIN_SAMPLES else "spot-check"
        ),
        "interval_method": (
            f"Wilson {conf:.0%} on P(spread<=bound); distribution-free "
            f"order-statistic {conf:.0%} CIs on quantiles (binomial rank "
            "bracket, normal-approx ranks)"
        ),
        "confidence": conf,
        "finished": finished,
        "spread_ticks_min": int(good[0]) if good.size else None,
        "spread_ticks_median": med,
        "median_ci": list(med_ci),
        "spread_ticks_p99": p99,
        "p99_ci": list(p99_ci),
        "p01_ci": list(p01_ci),
        "spread_ticks_max": int(good[-1]) if good.size else None,
        "within_bound": within,
        "p_within_bound": round(within / n_seeds, 6),
        "wilson": [round(wil[0], 6), round(wil[1], 6)],
        "spread_histogram": hist,
        "windows_dispatched": windows,
        "window_ticks": window,
        "fleet_devices": int(jax.device_count()) if sharded else 1,
        **bound,
        "certified": bool(certified),
    }


#: default MC matrix: >= 6 (strategy x topology) cells. r16 (ROADMAP 3a):
#: the PVIEW and SPARSE engines now run their own MC cells — the
#: statistical load no longer rests on the dense engine alone (r15 proved
#: their fleets bit-identical + audited but ran no MC matrix over them)
DEFAULT_MC_MATRIX = (
    ("push", "full", "dense"),
    ("push", "expander", "dense"),
    ("push_pull", "full", "dense"),
    ("push_pull", "expander", "dense"),
    ("accelerated", "expander", "dense"),
    ("accelerated", "ring", "dense"),
    ("tuneable", "expander", "dense"),
    ("pipelined", "expander", "dense"),
    ("push", "expander", "pview"),
    ("accelerated", "expander", "pview"),
    ("push", "full", "sparse"),
    ("push", "expander", "sparse"),
)


def mc_spread_certifier(
    matrix=None,
    n: int = 64,
    n_seeds: int = MC_MIN_SAMPLES,
    fanout: int = 3,
    rumor_slots: int = 8,
    window: int = 32,
    pipeline_budget: int = 2,
    geo_wan_delay_ticks: int = 2,
    base_seed: int = 0,
    bus=None,
    log=None,
) -> dict:
    """Run the Monte Carlo certification matrix (the r15 twin of
    :func:`spread_certifier`): one fleet program per cell, ``n_seeds``
    scenarios each, Wilson + order-statistic intervals recorded per
    entry. Returns the record ``benchmarks/config14_fleet.py`` writes
    into FLEET_BENCH_r15.json."""
    entries = []
    matrix = tuple(matrix or DEFAULT_MC_MATRIX)
    for strat, topol, engine in matrix:
        spec = DissemSpec(
            strategy=strat,
            topology=topol,
            geo_wan_delay_ticks=geo_wan_delay_ticks if topol == "geo" else 0,
            pipeline_budget=pipeline_budget,
        )
        rec = certify_spread_mc(
            spec, n=n, n_seeds=n_seeds, engine=engine, fanout=fanout,
            rumor_slots=rumor_slots, window=window, base_seed=base_seed,
        )
        entries.append(rec)
        if log:
            log(
                f"MC {engine}/{strat}/{topol}: {rec['finished']}/{n_seeds} "
                f"finished, median {rec['spread_ticks_median']} "
                f"p99 {rec['spread_ticks_p99']} "
                f"(CI {rec['p99_ci']}) <= bound {rec['bound_ticks']}; "
                f"P(within) wilson {rec['wilson']} "
                f"{'OK' if rec['certified'] else 'VIOLATION'}"
            )
        if bus is not None:
            bus.publish(
                "dissemination", "spread_certified_mc",
                strategy=strat, topology=topol, engine=engine,
                certified=rec["certified"], n_seeds=n_seeds,
                p99=rec["spread_ticks_p99"], p99_ci=rec["p99_ci"],
                bound_ticks=rec["bound_ticks"], wilson=rec["wilson"],
            )
    return {
        "n": n,
        "n_seeds": n_seeds,
        "fanout": fanout,
        "rumor_slots": rumor_slots,
        "window_ticks": window,
        "entries": entries,
        "certified_strategies": sorted(
            {e["strategy"] for e in entries if e["certified"]}
        ),
        "certified_topologies": sorted(
            {e["topology"] for e in entries if e["certified"]}
        ),
        "n_certified": sum(1 for e in entries if e["certified"]),
        "n_entries": len(entries),
        "total_trajectories": n_seeds * len(entries),
        "ok": all(e["certified"] for e in entries),
    }


# -- Monte Carlo false-positive certification (the chaos sentinel, S-wide) ---

#: the r14 loss-adversarial cohort layout fp_rate_mc drives (config13's
#: scenario, minus the delay-ring SlowMember so the MC fleet stays on the
#: loss planes only — delay rings multiply the batched state by D)
FP_MC_COHORT = dict(asym_rows=(5, 6, 7), flaky_rows=(9,), crash_row=20)


def fp_rate_mc(
    n: int = 48,
    n_seeds: int = 512,
    loss_floor=0.10,
    adaptive: bool = False,
    window: int = 16,
    until: int = 200,
    horizon: int = 240,
    crash_at: int = 30,
    base_seed: int = 0,
    static_suspicion_mult: int = 3,
    adaptive_knobs: Optional[dict] = None,
    conf: float = 0.95,
) -> dict:
    """Monte Carlo false-positive certification (the r14 sentinel's check,
    S-wide): ``n_seeds`` clusters run the loss-adversarial scenario
    (AsymmetricLoss cohort + FlakyObserver + one true Crash) over an
    ambient uniform-loss floor, driven by the BATCHED StateTimeline fold
    (:func:`..ops.fleet.fleet_timeline`); per-scenario false-DEAD maxima
    and crash-detection ticks latch on device at window boundaries (the
    sentinel sampling-soundness argument, unchanged) and read back ONCE.
    Reports the Wilson interval on P(any false-DEAD) — the number the
    adaptive arm must pin to ~0 while the static control's interval sits
    visibly above it — plus crash-detection latency quantiles against the
    static detection budget.

    ``loss_floor`` (r16, ROADMAP 3d): a scalar runs every scenario at one
    ambient floor as before; an ARRAY of floors splits the fleet across a
    condition grid in the SAME compiled program — scenario ``s`` runs at
    ``loss_floor[s % len(loss_floor)]`` (tiled), and the record gains a
    ``per_floor`` breakdown (per-floor false-DEAD Wilson intervals +
    detection maxima). This is the loss axis of the adaptive-knob sweep
    (:func:`adaptive_knob_sweep`)."""
    import jax
    import jax.numpy as jnp

    from ..adaptive import AdaptiveSpec, init_adaptive_state
    from ..chaos import events as ev
    from ..chaos.sentinels import default_detect_budget
    from ..ops import fleet as FL
    from ..ops import state as S
    from ..ops.state import SimParams

    knobs = adaptive_knobs or dict(
        min_mult=5, max_mult=10, conf_target=4, lh_max=8
    )
    spec = AdaptiveSpec(enabled=True, **knobs) if adaptive else AdaptiveSpec()
    params = SimParams(
        capacity=n, fd_every=1, sync_every=40,
        suspicion_mult=static_suspicion_mult, rumor_slots=8, seed_rows=(0,),
        full_metrics=False, adaptive=spec,
        quiet_gates=False,  # the fleet profile (see certify_spread_mc)
    )
    cohort = FP_MC_COHORT
    watch_rows = tuple(cohort["asym_rows"]) + tuple(cohort["flaky_rows"])
    crash_row = cohort["crash_row"]
    scen = ev.Scenario(
        name="loss_adversarial_mc_r15",
        events=(
            ev.AsymmetricLoss(rows=list(cohort["asym_rows"]), pct=70.0,
                              at=4, until=until, direction="in"),
            ev.FlakyObserver(rows=list(cohort["flaky_rows"]), pct=70.0,
                             at=4, until=until),
            ev.Crash(rows=[crash_row], at=crash_at),
        ),
        horizon=horizon,
    )
    # an ARRAY input (any length, even 1) means "grid mode": the record
    # carries the per_floor breakdown the knob sweep indexes into; a
    # scalar keeps the r15 record shape
    floor_is_grid = np.ndim(loss_floor) > 0
    floor_grid = np.atleast_1d(np.asarray(loss_floor, np.float32))
    floors_s = floor_grid[np.arange(n_seeds) % floor_grid.size]
    st0 = S.init_state(params, n, warm=True)
    fs = FL.fleet_broadcast(st0, n_seeds)
    if floor_grid.max() > 0:
        # per-scenario ambient floors (one floor when scalar) — the r16
        # varied-condition seam, one vmapped write before the first window
        fs = FL.fleet_uniform_loss(S, fs, floors_s)
    keys = FL.fleet_keys(base_seed + np.arange(n_seeds))
    ad = (
        FL.fleet_broadcast(init_adaptive_state(n), n_seeds)
        if adaptive else None
    )
    tl = FL.fleet_timeline(scen, S, dense_links=True, horizon=horizon)
    watch_mask = np.zeros((n,), bool)
    watch_mask[list(watch_rows)] = True
    watch_mask = jnp.asarray(watch_mask)

    steps: dict = {}  # window length -> jitted fleet program

    def _step(k: int):
        if k not in steps:
            steps[k] = (
                FL.make_fleet_adaptive_run(params, k) if adaptive
                else FL.make_fleet_run(params, k)
            )
        return steps[k]

    fold_fp = jax.jit(FL.fleet_false_dead)
    fold_det = jax.jit(lambda st: FL.fleet_crash_detected(st, crash_row))
    fp_max = jnp.zeros((n_seeds,), jnp.int32)
    det_tick = jnp.full((n_seeds,), -1, jnp.int32)
    boundaries = set(tl.boundaries())
    t = 0
    while t < horizon:
        fs, _labels = tl.apply_due(fs, t)
        stops = [horizon, t + window] + [b for b in boundaries if b > t]
        stop = min(s for s in stops if s > t)
        if adaptive:
            fs, ad, keys, _ms, _w = _step(stop - t)(fs, ad, keys)
        else:
            fs, keys, _ms, _w = _step(stop - t)(fs, keys)
        t = stop
        fp_max = jnp.maximum(fp_max, fold_fp(fs, watch_mask))
        if t > crash_at:
            det = fold_det(fs)
            det_tick = jnp.where(
                (det_tick < 0) & det, jnp.int32(t), det_tick
            )
    fs, _labels = tl.apply_due(fs, horizon)
    fp_np = np.asarray(fp_max)  # the one [S] readback pair
    det_np = np.asarray(det_tick)
    k_fp = int((fp_np > 0).sum())
    wil = wilson_interval(k_fp, n_seeds, conf)
    deadline = crash_at + default_detect_budget(params)
    detected = det_np[det_np >= 0]
    det_sorted = np.sort(detected)
    _p99d, p99d_ci = quantile_ci(det_sorted, 0.99, conf)
    det_ok = (
        int((det_np >= 0).sum()) == n_seeds
        and int(det_np.max()) <= deadline
    )
    per_floor = None
    if floor_is_grid:
        per_floor = []
        for f in floor_grid:
            m = floors_s == f
            kf, nf = int((fp_np[m] > 0).sum()), int(m.sum())
            wf = wilson_interval(kf, nf, conf)
            df = det_np[m]
            per_floor.append({
                "loss_floor_pct": round(float(f) * 100, 2),
                "n_seeds": nf,
                "false_dead_scenarios": kf,
                "fp_rate": round(kf / max(nf, 1), 6),
                "fp_rate_wilson": [round(wf[0], 6), round(wf[1], 6)],
                "crash_detected": int((df >= 0).sum()),
                "crash_detect_max": (
                    int(df.max()) if (df >= 0).any() else None
                ),
            })
    return {
        "arm": "adaptive" if adaptive else "static",
        "n": n,
        "n_seeds": n_seeds,
        "sample_size": n_seeds,
        "verdict_kind": (
            "monte-carlo" if n_seeds >= MC_MIN_SAMPLES else "spot-check"
        ),
        "loss_floor_pct": (
            [round(float(f) * 100, 2) for f in floor_grid] if floor_is_grid
            else round(float(floor_grid[0]) * 100, 2)
        ),
        "per_floor": per_floor,
        "scenario": scen.name,
        "fp_watch_rows": list(watch_rows),
        "false_dead_scenarios": k_fp,
        "fp_rate": round(k_fp / n_seeds, 6),
        "fp_rate_wilson": [round(wil[0], 6), round(wil[1], 6)],
        "interval_method": f"Wilson {conf:.0%} on P(false-DEAD > 0)",
        "crash_detected": int((det_np >= 0).sum()),
        "crash_detect_deadline": int(deadline),
        "crash_detect_max": int(det_np.max()) if detected.size else None,
        "crash_detect_p99_ci": list(p99d_ci),
        "crash_detect_window_ticks": window,
        "detections_ok": bool(det_ok),
        "static_suspicion_mult": static_suspicion_mult,
        "adaptive_knobs": knobs if adaptive else None,
    }


def adaptive_knob_sweep(
    min_mults: Sequence[int] = (3, 5, 8),
    conf_targets: Sequence[int] = (2, 4),
    loss_floors: Sequence[float] = (0.0, 0.10, 0.20),
    n: int = 48,
    n_seeds_per_floor: int = 171,
    window: int = 16,
    horizon: int = 240,
    base_seed: int = 0,
    fp_budget: float = 0.03,
    conf: float = 0.95,
    log=None,
) -> dict:
    """The offline adaptive-knob map (r16, ROADMAP 3b): ``fp_rate_mc``
    over a (min_mult × conf_target × loss-floor) grid.

    Knobs are STATIC program properties, so each (min_mult, conf_target)
    pair compiles its own fleet program; the LOSS axis rides the r16
    per-scenario floor variation — one fleet per knob pair sweeps every
    floor in the same compiled window (``n_seeds_per_floor`` scenarios
    per floor). ``max_mult`` tracks ``2 * min_mult`` (the r14 shipped
    ratio).

    The output is the map the closed-loop controller's ladder defaults
    are seeded from (``control.DEFAULT_LADDER``): per floor, the
    ``recommended`` entry is the FASTEST knob (lowest ``min_mult``,
    i.e. lowest time-to-DEAD) whose false-DEAD Wilson upper bound stays
    within ``fp_budget`` at that floor — the exact trade the controller
    makes on-line when the observed loss condition shifts."""
    floors = [float(f) for f in loss_floors]
    n_seeds = n_seeds_per_floor * len(floors)
    cells = []
    for mm in min_mults:
        for ct in conf_targets:
            knobs = dict(min_mult=int(mm), max_mult=int(2 * mm),
                         conf_target=int(ct), lh_max=8)
            rec = fp_rate_mc(
                n=n, n_seeds=n_seeds, loss_floor=np.asarray(floors),
                adaptive=True, window=window, horizon=horizon,
                base_seed=base_seed, adaptive_knobs=knobs, conf=conf,
            )
            cells.append(rec)
            if log:
                log(
                    f"knob map min_mult={mm} conf_target={ct}: fp/floor "
                    + " ".join(
                        f"{p['loss_floor_pct']}%:{p['fp_rate']:.3f}"
                        for p in rec["per_floor"]
                    )
                    + f" detect_max={rec['crash_detect_max']}"
                )
    recommended = {}
    for i, f in enumerate(floors):
        best = None
        for rec in cells:
            p = rec["per_floor"][i]
            if p["fp_rate_wilson"][1] <= fp_budget:
                k = rec["adaptive_knobs"]
                if best is None or k["min_mult"] < best["min_mult"]:
                    best = dict(
                        k, fp_rate=p["fp_rate"],
                        fp_rate_wilson=p["fp_rate_wilson"],
                        crash_detect_max=p["crash_detect_max"],
                    )
        recommended[str(round(f * 100, 2))] = best
    return {
        "n": n,
        "n_seeds_per_floor": n_seeds_per_floor,
        "min_mults": [int(m) for m in min_mults],
        "conf_targets": [int(c) for c in conf_targets],
        "loss_floor_pcts": [round(f * 100, 2) for f in floors],
        "fp_budget": fp_budget,
        "sample_size": n_seeds,
        "verdict_kind": (
            "monte-carlo" if n_seeds >= MC_MIN_SAMPLES else "spot-check"
        ),
        "cells": cells,
        #: per loss-floor pct: the fastest knob within the fp budget —
        #: what seeds control.DEFAULT_LADDER
        "recommended": recommended,
    }
