"""Strategy resolution helpers the three gossip phases share (r13).

The seam is deliberately tiny: a strategy is (a) a PEER-SELECTION rule —
which ``fanout`` targets each sender contacts this tick — plus (b) a
PAYLOAD-BUDGET rule — which user-rumor slots ride the message — plus (c)
an optional PULL REPLY leg. Everything is elementwise integer/f32 math
computable under ``xp=jnp`` (the kernels) and ``xp=np`` (the scalar
oracles) with bit-identical results, which is what keeps every (engine ×
strategy) window in oracle lockstep.

Deviations from the cited papers, stated once:

* **DZ-1 (overlay vs view).** On a structured topology, sends are gated
  on the PHYSICAL liveness of both endpoints (``up[src] & up[dst]`` —
  the same edge gate as always) but NOT on the sender's membership view
  of the target: the overlay is configured wiring, and a member does not
  stop using a static link because it currently suspects the neighbor.
  Membership semantics are unaffected — every record still enters
  through the same monotone merge gates.
* **DZ-2 (pull replies ride the contact).** A ``push_pull`` reply is
  sent by a peer that a payload-bearing message REACHED this tick
  (undelayed contacts only) and lands immediately: the reply shares the
  round trip the push established, like the reference's request/response
  exchanges. Its delivery draw is an independent hashed uniform on the
  reverse link (``SALT_PULL`` family, ops/rand.py).
* **DZ-3 (budget throttles user rumors only).** The pipelined budget
  rotates over USER-rumor slots; membership records (failure-detection
  plumbing) are never throttled — safety traffic is not subject to the
  bandwidth experiment.
* **DZ-4 (duplicate chords).** When ``fanout`` exceeds the chord count,
  deterministic schedules revisit chords within a tick (distinct edge
  draws, idempotent merges) rather than refusing the configuration.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..ops.rand import SALT_PULL, SALT_PULL_STRIDE
from . import topology


def pull_salt(s: int) -> int:
    """Per-fanout-slot salt of the pull-reply delivery draw (slots must
    not share draws; see the salt-spacing rule in ops/rand.py)."""
    return SALT_PULL + s * SALT_PULL_STRIDE


def structured_peers(spec, n: int, tick, u_sel, xp=jnp):
    """Closed-form circulant peer selection: ``peers [N, F] i32`` +
    ``valid [N, F]`` (always true — DZ-1/DZ-4). ``u_sel`` is the [N, F]
    uniform block the engine's sampler would have consumed (only the
    random strategies read it; the deterministic schedules ignore it, but
    the draw stream is generated either way so arming a strategy never
    perturbs the other phases' randomness)."""
    ch = topology.chords(spec, n)
    C = len(ch)
    F = u_sel.shape[1]
    rows = xp.arange(n, dtype=xp.int32)
    ch_arr = xp.asarray(np.asarray(ch, np.int32))
    cols = []
    for s in range(F):
        if spec.strategy in ("push", "push_pull"):
            ci = xp.minimum(
                (u_sel[:, s] * np.float32(C)).astype(xp.int32), C - 1
            )
        elif spec.strategy == "pipelined":
            ci = (tick * F + s) % C
        elif spec.strategy == "tuneable":
            # robust/tuneable family (arXiv:1506.02288): deterministic
            # doubling-walk chord with probability ``mix``, else a uniform
            # chord from the SAME per-slot uniform's residual (one draw
            # serves both the decision and the random pick, so arming the
            # family never perturbs the engines' draw streams)
            ci = _tuneable_chord(spec, C, tick, s, u_sel[:, s], xp=xp)
        else:  # accelerated — the doubling walk
            ci = (tick + s) % C
        cols.append((rows + ch_arr[ci]) % n)
    peers = xp.stack(cols, 1).astype(xp.int32)
    valid = xp.ones((n, F), bool)
    return peers, valid


def _tuneable_chord(spec, C: int, tick, s: int, u, xp=jnp):
    """The tuneable family's per-slot chord index (xp-generic, elementwise
    f32 — identical under jnp and np, which is the oracle-lockstep
    contract). ``u < mix`` follows the deterministic walk; otherwise the
    residual ``(u - mix) / (1 - mix)`` rescales into a uniform chord draw."""
    mix = np.float32(spec.tuneable_mix)
    det = xp.asarray((tick + s) % C, dtype=xp.int32)
    if spec.tuneable_mix >= 1.0:
        return xp.broadcast_to(det, xp.shape(u)).astype(xp.int32)
    if spec.tuneable_mix <= 0.0:
        return xp.minimum((u * np.float32(C)).astype(xp.int32), C - 1)
    u2 = (u - mix) / np.float32(1.0 - mix)
    rand = xp.clip((u2 * np.float32(C)).astype(xp.int32), 0, C - 1)
    return xp.where(u < mix, det, rand).astype(xp.int32)


def structured_peer_row(spec, n: int, tick: int, i: int, u_row):
    """Scalar-oracle mirror of :func:`structured_peers` for one sender row
    — identical f32 trunc-multiply and modular arithmetic."""
    ch = topology.chords(spec, n)
    C = len(ch)
    F = len(u_row)
    peers = np.zeros(F, np.int32)
    for s in range(F):
        if spec.strategy in ("push", "push_pull"):
            ci = min(int(np.float32(u_row[s]) * np.float32(C)), C - 1)
        elif spec.strategy == "pipelined":
            ci = (tick * F + s) % C
        elif spec.strategy == "tuneable":
            ci = int(
                _tuneable_chord(
                    spec, C, tick, s, np.float32(u_row[s]), xp=np
                )
            )
        else:
            ci = (tick + s) % C
        peers[s] = (i + ch[ci]) % n
    return peers, np.ones(F, bool)


def try_stride_uniforms(u_try, tries: int):
    """The [N, F] uniform block a rejection-sampling engine (sparse/pview)
    hands to the random structured selection: the FIRST try column of each
    pick (one uniform per pick, the rest of the try block unread)."""
    return u_try[:, ::tries]


def rumor_budget_mask(spec, n_slots: int, tick, xp=jnp):
    """Pipelined payload budget: the [R] bool window of user-rumor slots a
    message may carry this tick (rotating, ``pipeline_budget`` wide), or
    ``None`` for the unthrottled strategies (DZ-3)."""
    if spec.strategy != "pipelined":
        return None
    b = min(spec.pipeline_budget, n_slots)
    idx = xp.arange(n_slots, dtype=xp.int32)
    return ((idx - tick) % n_slots) < b


def budget_ok(spec, slot: int, tick: int, n_slots: int) -> bool:
    """Scalar-oracle mirror of :func:`rumor_budget_mask` for one slot."""
    if spec.strategy != "pipelined":
        return True
    b = min(spec.pipeline_budget, n_slots)
    return ((slot - tick) % n_slots) < b
