"""Static dissemination-strategy spec (r13).

One frozen, hashable dataclass describes WHICH gossip strategy the tick's
dissemination phase runs and ON WHAT overlay topology the fanout peers are
drawn — it rides every engine's static params object (``SimParams`` /
``SparseParams`` / ``PviewParams``), so the strategy is a compile-time
property of the window program: the default spec traces the byte-identical
program the repo has always shipped, and a non-default spec swaps ONLY the
gossip phase's peer selection / payload policy (FD probes and SYNC
anti-entropy keep the reference's uniform semantics untouched).

Strategies (PAPERS.md upgrades over uniform-random push):

* ``push`` — the shipped default: every sender pushes its payload to
  ``fanout`` peers per tick. On ``full`` it keeps the engine's own
  live-view sampler (bit-identical legacy program); on a structured
  topology the peers are random chords of the overlay.
* ``push_pull`` — push plus a pull reply: a peer that receives a payload-
  bearing message answers the same round trip with ITS young records and
  rumors (the anti-entropy phase of Karp et al.'s push-pull; referenced by
  arXiv:1504.03277 §1). Replies ride undelayed contacts only and share
  the established round trip (deviation DZ-2, see strategies.py).
* ``pipelined`` — pipelined gossip (arXiv:1504.03277): deterministic
  round-robin rotation over the topology's chord set plus a per-message
  USER-RUMOR budget of ``pipeline_budget`` slots selected by a rotating
  window — concurrent rumors share the wire in a pipeline instead of
  competing, which is the paper's steady-state-rate claim. Membership
  dissemination (failure-detection plumbing) is never throttled.
* ``accelerated`` — topology-structured deterministic schedule
  (arXiv:1805.08531's lesson transplanted to rumor spreading: exploit the
  graph's structure with a fixed polynomial-style iteration instead of
  uniform randomness; the rumor-spreading analogue is the doubling-chord
  schedule of randomness-efficient spreading, arXiv:1311.2839): each tick
  sends along ``fanout`` consecutive chords of the ascending chord set,
  advancing one chord per tick — on geometric chord sets the infected
  interval doubles per covered chord, giving a DETERMINISTIC O(log N)
  bound.
* ``tuneable`` — the robust/tuneable gossip family (arXiv:1506.02288,
  "A Robust and Tuneable Family of Gossiping Algorithms"): each send
  follows the deterministic doubling walk with probability
  ``tuneable_mix`` and an independently drawn uniform chord otherwise —
  one knob trades the deterministic schedule's speed against the
  randomized family's robustness to adversarial loss/crashes (the paper's
  interpolation, transplanted to circulant chord selection). ``mix=1``
  degenerates to the accelerated walk, ``mix=0`` to uniform random
  chords; both halves consume the SAME per-slot uniform (the decision's
  residual rescales into the random chord draw), so the engine draw
  stream is untouched.

Topologies (circulant overlays — every neighbor is ``(i + chord) mod N``,
so pview never materializes an [N, N] adjacency and even the dense engine
pays only O(N·fanout) selection work):

* ``full`` — no overlay constraint (uniform strategies use the live-view
  sampler; deterministic strategies synthesize a doubling chord set — a
  virtual hypercube).
* ``ring`` — chords {1, N-1}: the linear-diameter worst case.
* ``torus`` — chords {1, N-1, c, N-c} for an r x c wrap (2-D diameter).
* ``expander`` — odd geometric chords {1, 3, 5, 9, 17, ...}: a circulant
  expander with O(log N) diameter (odd so the chord set never traps a
  residue class — the pview warm-overlay lesson).
* ``geo`` — ``geo_zones`` contiguous zones: doubling chords WITHIN the
  zone plus one WAN chord to the next zone; ``geo_wan_delay_ticks`` is
  the mean extra delay the certifier applies to every cross-zone link
  (dense engine's per-link delay matrix — WAN-like delay rings).
"""

from __future__ import annotations

import dataclasses

STRATEGIES = ("push", "push_pull", "pipelined", "accelerated", "tuneable")
TOPOLOGIES = ("full", "ring", "torus", "expander", "geo")


@dataclasses.dataclass(frozen=True)
class DissemSpec:
    """Hashable static dissemination spec (defaults = the legacy program)."""

    strategy: str = "push"
    topology: str = "full"
    #: chord-count budget for expander/geo (0 = auto ceil_log2)
    degree: int = 0
    #: torus row count (0 = auto: largest divisor of N at or below sqrt(N))
    torus_rows: int = 0
    geo_zones: int = 4
    #: mean cross-zone link delay in ticks (host-applied by the certifier /
    #: bench on the dense engine's delay matrix; 0 = no WAN delay)
    geo_wan_delay_ticks: int = 0
    #: pipelined: user-rumor slots carried per message (rotating window)
    pipeline_budget: int = 1
    #: tuneable: probability each send follows the deterministic doubling
    #: walk instead of a uniform random chord (arXiv:1506.02288's knob)
    tuneable_mix: float = 0.5

    def __post_init__(self):
        if self.strategy not in STRATEGIES:
            raise ValueError(
                f"unknown strategy {self.strategy!r}; one of {STRATEGIES}"
            )
        if self.topology not in TOPOLOGIES:
            raise ValueError(
                f"unknown topology {self.topology!r}; one of {TOPOLOGIES}"
            )
        if self.degree < 0:
            raise ValueError("degree must be >= 0 (0 = auto)")
        if self.torus_rows < 0:
            raise ValueError("torus_rows must be >= 0 (0 = auto)")
        if self.geo_zones < 2:
            raise ValueError("geo_zones must be >= 2")
        if self.geo_wan_delay_ticks < 0:
            raise ValueError("geo_wan_delay_ticks must be >= 0")
        if self.pipeline_budget < 1:
            raise ValueError("pipeline_budget must be >= 1")
        if not (0.0 <= self.tuneable_mix <= 1.0):
            raise ValueError("tuneable_mix must be in [0, 1]")

    # -- static program-shape switches ---------------------------------------
    @property
    def is_default(self) -> bool:
        """True iff the spec selects the byte-identical legacy program."""
        return self.strategy == "push" and self.topology == "full"

    @property
    def uniform_selection(self) -> bool:
        """Peer selection stays the engine's own live-view sampler (the
        random strategies on the unconstrained topology)."""
        return self.topology == "full" and self.strategy in ("push", "push_pull")

    @property
    def deterministic(self) -> bool:
        return self.strategy in ("pipelined", "accelerated")

    @property
    def wants_pull(self) -> bool:
        return self.strategy == "push_pull"

    @staticmethod
    def from_config(config) -> "DissemSpec":
        """Map a ``ClusterConfig.dissemination`` block (or an absent one)
        onto a spec."""
        dc = getattr(config, "dissemination", None)
        if dc is None:
            return DissemSpec()
        return DissemSpec(
            strategy=dc.strategy,
            topology=dc.topology,
            degree=dc.degree,
            torus_rows=dc.torus_rows,
            geo_zones=dc.geo_zones,
            geo_wan_delay_ticks=dc.geo_wan_delay_ticks,
            pipeline_budget=dc.pipeline_budget,
            tuneable_mix=getattr(dc, "tuneable_mix", 0.5),
        )


#: the one shared default instance (``params.dissem`` default value)
DEFAULT = DissemSpec()
