"""Adaptive failure-detection plane (r14): Lifeguard-style local health +
confirmation-scaled suspicion.

Static SWIM deployments pick one probe timeout and one suspicion multiplier
and hope both fit every member forever. Lifeguard (the SWIM extension
hashicorp/memberlist ships) showed the false-positive rate collapses when
each member *adapts* those constants to evidence: a member whose own probes
keep missing (it is slow, lossy, or GC-bound) should trust its own verdicts
less — stretching the timers it originates — while a suspicion corroborated
by many independent members can be declared DEAD fast. This module is that
plane for all three tick engines (dense ``ops/kernel.py``, sparse
``ops/sparse.py``, pview ``ops/pview.py``), under the repo's r13 discipline:

* **One hashable spec** (:class:`AdaptiveSpec`) rides every engine's static
  params object as ``params.adaptive``. The DEFAULT spec traces the
  byte-identical legacy window program — adaptive state, phases, and
  arithmetic exist only in windows built from an ``enabled=True`` spec
  (``make_adaptive_run``), so default users cannot pay for any of this.
* **One state pytree** (:class:`AdaptiveState`), identical across engines —
  three [N] int32 planes, no [N, N] anywhere (the pview wide-value ban
  holds over adaptive windows too):

  - ``lh`` — the per-member **local-health score** (Lifeguard's LHA
    multiplier): saturating counter in ``[0, lh_max]``, +1 per failed own
    probe round, +1 per self-refutation (someone suspected ME — evidence I
    look flaky from outside), −1 per acked probe round. A member's own
    direct-probe timeout and the suspicion sweeps it runs both scale by
    ``(1 + lh)``.
  - ``conf_key`` / ``conf`` — the per-subject **suspicion-confirmation
    episode**: ``conf_key[j]`` is the highest SUSPECT-rank precedence key
    accepted about ``j`` so far and ``conf[j]`` counts accepted SUSPECT
    records at (or below) that episode, saturating at ``conf_target``. A
    higher-key SUSPECT accept supersedes the episode and restarts the
    count. The suspicion time-to-DEAD interpolates log-scaled from
    ``max_mult`` (lone accusation) down to ``min_mult`` (fully
    corroborated) — Lifeguard's timeout schedule in integer math.

* **Bit-exact oracles.** Every formula here is xp-generic (``xp=jnp`` in
  the kernels, ``xp=np`` in the scalar oracles) pure integer/f32 work with
  no transcendentals, so each engine's adaptive window stays in FULL-state
  lockstep with its per-node scalar oracle.

Deviations from the Lifeguard/reference mechanisms, stated once:

* **AD-1 (global confirmation episodes).** Lifeguard counts per-observer
  suspicion confirmations carried in suspect messages; this repo's records
  carry no suspector identity, so confirmations are counted globally per
  SUBJECT — one counter incremented by every accepted SUSPECT record about
  the subject anywhere (FD verdicts, gossip merges, SYNC merges alike).
  This is the same modelling move the sparse engine's suspicion episodes
  (``sus_key``/``sus_since``, its deviation 1) already made for the timer
  itself. An observer's sweep consults the counter only for cells whose
  key is within the episode (``cell_key <= conf_key``), so a NEWER
  suspicion never inherits a stale episode's confirmations.
* **AD-2 (redelivery ≈ independence).** Without suspector identities, k
  accepted copies of a SUSPECT record approximate k independent
  suspectors. Over-counting only *shortens* the window toward
  ``min_mult`` — never below the static engine's floor when ``min_mult >=
  suspicion_mult`` (the shipped default).
* **AD-3 (observer-side scaling).** Lifeguard scales the timers of the
  member that *originates* a suspicion. Per-cell origin bits would cost a
  wide plane, so the sweep scales by the OBSERVER's ``(1 + lh)`` — every
  suspicion a degraded observer is aging, whether it originated it or
  merely accepted it, ages slowly. Strictly more conservative.
* **AD-4 (direct leg only).** Only the direct-probe timeout stretches with
  ``lh`` (``fd_direct_timeout_ticks * (1 + lh)``, capped by ``lh_max``);
  indirect-probe legs and SYNC keep their static budgets. The indirect
  path exists precisely to route around the prober's own link, so
  stretching it would mask exactly the evidence ``lh`` measures. Timeout
  scaling is live only under the delay model (``params.delay_slots > 0``)
  — without modelled delay there is no timeout to beat, which the
  closed-form timeliness factor makes exact (factor 1.0).
* **AD-5 (refutes are never throttled).** A suspected member's refutation
  (the ``bump_inc`` incarnation bump) is a MEMBERSHIP record: it rides the
  gossip stream's unbudgeted class (dissemination deviation DZ-3), so no
  pipelined/tuneable payload budget can delay the fast path that clears a
  false suspicion. This was already true; the adaptive plane depends on
  it, so tests pin it.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

#: scatter-max identity shared with the engines' key planes
NO_CANDIDATE = int(np.iinfo(np.int32).min)


@dataclasses.dataclass(frozen=True)
class AdaptiveSpec:
    """Hashable static adaptive-FD spec (defaults = the legacy program).

    ``enabled=False`` (the default) is the r13 discipline's off switch: the
    window builders trace the byte-identical legacy program and no adaptive
    state exists. ``enabled=True`` arms all three mechanisms; the knobs:

    * ``lh_max`` — local-health score ceiling (Lifeguard caps its score;
      the probe timeout and sweep scale by at most ``1 + lh_max``).
    * ``min_mult`` / ``max_mult`` — the suspicion-multiplier range the
      confirmation count interpolates across (legacy uses the single
      ``params.suspicion_mult``; keep ``min_mult >= suspicion_mult`` to
      never declare faster than the static engine would have).
    * ``conf_target`` — confirmations at which the multiplier reaches
      ``min_mult`` (the count saturates here).
    """

    enabled: bool = False
    lh_max: int = 8
    min_mult: int = 5
    max_mult: int = 10
    conf_target: int = 4

    def __post_init__(self):
        if self.lh_max < 0:
            raise ValueError("lh_max must be >= 0")
        if self.min_mult < 1:
            raise ValueError("min_mult must be >= 1")
        if self.max_mult < self.min_mult:
            raise ValueError("max_mult must be >= min_mult")
        if self.conf_target < 1:
            raise ValueError("conf_target must be >= 1")

    @property
    def is_default(self) -> bool:
        """True iff the spec selects the byte-identical legacy program."""
        return not self.enabled

    @property
    def levels(self) -> int:
        """The log-scale denominator L = bit_length(conf_target) — static."""
        return max(1, int(self.conf_target).bit_length())

    @staticmethod
    def from_config(config) -> "AdaptiveSpec":
        """Map a ``ClusterConfig.adaptive`` block (or an absent one)."""
        ac = getattr(config, "adaptive", None)
        if ac is None:
            return AdaptiveSpec()
        return AdaptiveSpec(
            enabled=ac.enabled,
            lh_max=ac.lh_max,
            min_mult=ac.min_mult,
            max_mult=ac.max_mult,
            conf_target=ac.conf_target,
        )


#: the one shared default instance (``params.adaptive`` default value)
DEFAULT = AdaptiveSpec()


class AdaptiveState(struct.PyTreeNode):
    """The adaptive plane's device state — identical shape for all three
    engines: three [N] i32 planes (see the module docstring). Donated
    alongside the engine state by ``make_adaptive_run``."""

    lh: jax.Array  # i32 [N] — local-health score, in [0, lh_max]
    conf_key: jax.Array  # i32 [N] — suspicion episode key (NO_CANDIDATE none)
    conf: jax.Array  # i32 [N] — confirmations, saturated at conf_target


def init_adaptive_state(capacity: int) -> AdaptiveState:
    return AdaptiveState(
        lh=jnp.zeros((capacity,), jnp.int32),
        conf_key=jnp.full((capacity,), NO_CANDIDATE, jnp.int32),
        conf=jnp.zeros((capacity,), jnp.int32),
    )


def adaptive_state_arrays(ad: AdaptiveState) -> dict:
    """Checkpoint view (host numpy) of the adaptive planes."""
    return {
        "_ad_lh": np.asarray(ad.lh),
        "_ad_conf_key": np.asarray(ad.conf_key),
        "_ad_conf": np.asarray(ad.conf),
    }


def restore_adaptive_state(arrays: dict) -> AdaptiveState:
    """Inverse of :func:`adaptive_state_arrays` — ``jnp.array(copy=True)``
    like every engine restore (the planes are donated; a zero-copy npz
    alias would be the r6 use-after-free)."""
    return AdaptiveState(
        lh=jnp.array(arrays["_ad_lh"], copy=True),
        conf_key=jnp.array(arrays["_ad_conf_key"], copy=True),
        conf=jnp.array(arrays["_ad_conf"], copy=True),
    )


# ---------------------------------------------------------------------------
# shared math (xp-generic: jnp in the kernels, np in the scalar oracles)
# ---------------------------------------------------------------------------


def bit_length(x, xp=jnp):
    """Elementwise ``int.bit_length`` for small non-negative int arrays —
    the same compare-and-count spelling as ``kernel.ceil_log2`` (not
    imported: this module must stay engine-agnostic)."""
    x = xp.asarray(x).astype(xp.int32)
    return (
        (x[..., None] >= (1 << xp.arange(31, dtype=xp.int32)))
        .sum(-1)
        .astype(xp.int32)
    )


def conf_mult_num(spec: AdaptiveSpec, conf, xp=jnp):
    """Numerator of the confirmation-scaled suspicion multiplier, per
    subject: ``max_mult*L - (max_mult - min_mult) * bit_length(min(conf,
    K))`` with ``L = bit_length(K)``. The sweep computes ``timeout = base *
    num * (1 + lh) // L`` — all integer, so kernels and oracles agree
    bit-for-bit. At conf=0 the multiplier is ``max_mult``; at conf>=K it is
    exactly ``min_mult`` (``bit_length(K) == L``)."""
    L = spec.levels
    c = xp.minimum(xp.asarray(conf).astype(xp.int32), spec.conf_target)
    return (
        xp.int32(spec.max_mult * L)
        - xp.int32(spec.max_mult - spec.min_mult) * bit_length(c, xp=xp)
    ).astype(xp.int32)


def conf_mult_num_scalar(spec: AdaptiveSpec, conf: int) -> int:
    """Scalar-oracle mirror of :func:`conf_mult_num` for one subject."""
    L = spec.levels
    c = min(int(conf), spec.conf_target)
    return spec.max_mult * L - (spec.max_mult - spec.min_mult) * int(c).bit_length()


def fold(
    spec: AdaptiveSpec,
    lh,
    conf_key,
    conf,
    *,
    acc_key,
    acc_cnt,
    miss,
    succ,
    refuted,
    up,
    xp=jnp,
):
    """End-of-tick adaptive-state fold — ONE spelling for kernels (xp=jnp)
    and oracles (xp=np). All of a tick's evidence lands here:

    * ``miss``/``succ`` [N] bool — this tick's own-probe outcome (FD rounds
      only; both False off-round). ``refuted`` [N] bool — the refute phase
      fired for the row. lh moves by ``miss + refuted - succ``, clamps to
      ``[0, lh_max]``, and resets to 0 for down rows (a restarted identity
      starts healthy).
    * ``acc_key``/``acc_cnt`` [N] — per-subject max accepted SUSPECT key
      and total accepted-SUSPECT count across every merge site this tick.
      A higher key supersedes the episode (count restarts at this tick's
      arrivals); an equal-or-lower key confirms it. The count saturates at
      ``conf_target`` (the multiplier is flat beyond it).

    The fold runs on PRE-tick adaptive state: phases read the previous
    tick's scores, which keeps phase order out of the adaptive semantics
    and makes the oracle mirror trivial.

    Returns ``(lh', conf_key', conf')``.
    """
    i32 = xp.int32
    lh2 = (
        xp.asarray(lh).astype(i32)
        + xp.asarray(miss).astype(i32)
        + xp.asarray(refuted).astype(i32)
        - xp.asarray(succ).astype(i32)
    )
    lh2 = xp.clip(lh2, 0, spec.lh_max).astype(i32)
    lh_new = xp.where(xp.asarray(up), lh2, i32(0)).astype(i32)
    ck = xp.asarray(conf_key).astype(i32)
    ak = xp.asarray(acc_key).astype(i32)
    supersede = ak > ck
    conf_key_new = xp.maximum(ck, ak).astype(i32)
    base = xp.where(supersede, i32(0), xp.asarray(conf).astype(i32))
    conf_new = xp.minimum(
        base + xp.asarray(acc_cnt).astype(i32), spec.conf_target
    ).astype(i32)
    return lh_new, conf_key_new, conf_new


def scaled_timely_rt(q1, q2, t_base: int, lh, lh_max: int, xp=jnp):
    """Lifeguard-scaled direct-probe timeliness: the closed-form
    ``P(round trip <= t_base * (1 + lh))`` under the geometric link-delay
    model, per row. Runs the SAME f32 convolution recurrence as
    ``kernel._timely_rt`` out to ``t_base * (1 + lh_max)`` steps, capturing
    the partial sum at every multiple of ``t_base``; each row selects its
    own capture. The captured value after ``t`` steps is bit-identical to
    running the legacy recurrence for ``t`` steps, so the scalar oracle
    mirrors this with a plain ``_timely(q1, q2, t_base * (1 + lh_i))``."""
    if t_base <= 0:
        one = xp.ones_like(q1)
        return ((1.0 - q1) * (1.0 - q2) * one).astype(xp.float32)
    h = xp.ones_like(q1)
    acc = h
    q2p = xp.ones_like(q2)
    captures = []
    for step in range(1, t_base * (1 + lh_max) + 1):
        q2p = q2p * q2
        h = q1 * h + q2p
        acc = acc + h
        if step % t_base == 0:
            captures.append(acc)
    table = xp.stack(captures, 0)  # [1 + lh_max, ...rows]
    idx = xp.clip(xp.asarray(lh).astype(xp.int32), 0, lh_max)
    sel = xp.take_along_axis(table, idx[None, ...], axis=0)[0]
    return ((1.0 - q1) * (1.0 - q2) * sel).astype(xp.float32)
