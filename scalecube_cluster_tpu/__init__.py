"""scalecube_cluster_tpu — a TPU-native SWIM cluster-membership framework.

Capability parity with ``scalecube-cluster`` (decentralized membership,
random-probe failure detection, infection-style gossip, SYNC anti-entropy)
built TPU-first: the protocol engine is a vectorized JAX/XLA tick kernel over
sharded state tensors (see ``ops/`` and ``parallel/``), while a scalar
asyncio engine (``cluster/``) provides the reference-equivalent per-node
implementation behind the same pluggable ``Transport`` boundary
(``transport/``).

Public API mirrors the reference ``Cluster`` facade (``Cluster.java:10-151``).
"""

from .compile_cache import (
    compile_cache_report,
    enable_persistent_compile_cache,
)
from .config import (
    ChaosConfig,
    ClusterConfig,
    ControlConfig,
    DisseminationConfig,
    FailureDetectorConfig,
    GossipConfig,
    MembershipConfig,
    SimConfig,
    TelemetryConfig,
    TraceConfig,
    TransportConfig,
)
from .models.events import FailureDetectorEvent, MembershipEvent, MembershipEventType
from .models.member import Member, MemberStatus, new_member_id
from .models.message import Message
from .models.record import MembershipRecord
from .version import __version__

__all__ = [
    "ChaosConfig",
    "ClusterConfig",
    "ControlConfig",
    "DisseminationConfig",
    "FailureDetectorConfig",
    "GossipConfig",
    "MembershipConfig",
    "TransportConfig",
    "SimConfig",
    "TelemetryConfig",
    "TraceConfig",
    "Member",
    "MemberStatus",
    "MembershipRecord",
    "MembershipEvent",
    "MembershipEventType",
    "FailureDetectorEvent",
    "Message",
    "new_member_id",
    "enable_persistent_compile_cache",
    "compile_cache_report",
    "__version__",
]
