"""Hybrid bridge: real ``Cluster`` processes inside a simulated membership.

``TpuSimTransport`` implements the same ``Transport`` contract as
``TcpTransport`` / ``WebsocketTransport`` (``transport/api.py:65``), but its
"network" is a :class:`SimBridge` that splices the endpoint into a live
:class:`~scalecube_cluster_tpu.sim.driver.SimDriver`: the bridged process
occupies one simulated row, every addressed sim row is materialized as a
host-side proxy endpoint, and the two protocol planes meet at the window
boundary (PAPER.md §1's pluggable-transport promise — "small real
configurations and huge simulated configurations run the same protocol
logic").

Direction real → sim (the proxy plane, all host-side, all OUTSIDE the jit):

* ``Q_PING`` / ``Q_PING_REQ`` — answered from the driver's host-visible
  truth: an up row whose occupant id matches acks ``DEST_OK``, an id
  mismatch (row re-occupied after restart) acks ``DEST_GONE`` exactly like
  the reference (``FailureDetectorImpl.onPing:300-320``), and a down row
  stays silent so the caller's timeout drives SUSPECT.
* ``Q_MEMBERSHIP_SYNC`` / ``SYNC_ACK`` — the sender's own record is folded
  into the driver as host mutations on the existing ``spread_rumor`` /
  ``crash_rows`` seam (incarnation bump → ``update_metadata``, LEAVING →
  ``leave``); a SYNC against an up row is answered with a full-table
  ``SyncData`` synthesized from ``view_of`` (one coalesced readback).
* ``Q_METADATA_REQ`` — answered for the row's current occupant (the
  reference answers only for its own id, ``MetadataStoreImpl:146-185``);
  this is the gate real membership requires before accepting ALIVE.
* ``Q_GOSSIP_REQ`` — deduplicated by gossip id; membership gossip about the
  sender folds like SYNC, user gossip folds into ``driver.spread_rumor``.

Direction sim → real (the window-boundary fold): each bridged row is a
watched row, so its per-window view diffs ride the ONE stacked
``[n_ticks, W, N]`` readback the r10 watch plane already pays — no new
in-scan consumers, the r12 audit matrix stays green (``tools/audit_programs
--variants bridge`` proves it). Events accumulated during a window are
coalesced into a single ``Q_MEMBERSHIP_SYNC`` message per endpoint whose
records take status + incarnation straight from the post-window key
snapshot (``_Watch.prev_key``), then merged by the real member's ordinary
serial ``_sync_membership`` path — one message per window instead of a
per-event gossip storm.

Deviations vs the reference netty transport are catalogued in
``docs/SERVING.md`` (§ deviations): bridged-member liveness toward the sim
is authored by the bridge link state (``fail_link`` / ``heal_link``), never
by third-party gossip, and sim-side user rumors are not surfaced to bridged
members (the rumor payload plane is host-tracked per driver, not per row).
"""

from __future__ import annotations

import asyncio
import random
import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

import numpy as np

from ..cluster.failure_detector import AckType, PingData
from ..cluster.membership import SyncData
from ..cluster.metadata import GetMetadataRequest, GetMetadataResponse
from ..models.member import Member, MemberStatus
from ..models.message import (
    HEADER_CORRELATION_ID,
    HEADER_SENDER,
    Message,
    Q_GOSSIP_REQ,
    Q_MEMBERSHIP_GOSSIP,
    Q_MEMBERSHIP_SYNC,
    Q_MEMBERSHIP_SYNC_ACK,
    Q_METADATA_REQ,
    Q_METADATA_RESP,
    Q_PING,
    Q_PING_ACK,
    Q_PING_REQ,
)
from ..models.record import MembershipRecord
from ..transport.api import (
    Listeners,
    PeerUnavailableError,
    Transport,
    TransportError,
    TransportEvent,
    register_transport_factory,
)
from ..transport.codecs import PickleMetadataCodec
from ..config import TransportConfig
from ..sim.driver import SimDriver, _status_of_key, row_address

BRIDGE_SCHEME = "tpusim://"

#: DEAD in the packed key maps through MemberStatus; UNKNOWN (no record /
#: forgotten row) folds to DEAD for record synthesis — to a real member a
#: forgotten row is simply gone.
_GONE = MemberStatus.DEAD


def _parse_sim_row(address: str) -> int:
    return int(address[len("sim://"):])


class BridgeError(TransportError):
    """Misuse of the bridge plane (bad address scheme, double attach...)."""


class SimBridge:
    """Hub joining a handful of real processes to one simulated membership.

    Owns the proxy plane for ``sim://`` addresses and the window-boundary
    fold for each bridged endpoint. All mutations of the driver go through
    its public host-mutation seam (``join`` / ``leave`` / ``crash`` /
    ``update_metadata`` / ``spread_rumor``) under the driver lock, so they
    land in the next stepped window like any other scripted churn.
    """

    _default: "Optional[SimBridge]" = None

    def __init__(
        self,
        driver: SimDriver,
        *,
        seed_rows=(0,),
        config: Optional[TransportConfig] = None,
    ) -> None:
        self._d = driver
        self._seed_rows = tuple(seed_rows)
        self._config = config or TransportConfig()
        self._endpoints: Dict[str, TpuSimTransport] = {}
        self._codec = PickleMetadataCodec()
        # bridge-wide gossip dedup: every proxy row a GossipRequest fans out
        # to would otherwise fold the same rumor again (bounded LRU)
        self._seen_gossip: "OrderedDict[str, bool]" = OrderedDict()
        self._seq = 0
        self._lock = threading.Lock()

    # -- endpoint factory ---------------------------------------------------
    def transport(
        self, name: Optional[str] = None, config: Optional[TransportConfig] = None
    ) -> "TpuSimTransport":
        """Create an (unstarted) endpoint; ``Cluster.start`` will start it."""
        with self._lock:
            if name is None:
                name = f"node-{self._seq}"
                self._seq += 1
            if name in self._endpoints and not self._endpoints[name].is_stopped:
                raise BridgeError(f"bridged endpoint {name!r} already attached")
        return TpuSimTransport(self, name, config or self._config)

    def transport_factory(
        self, name: Optional[str] = None
    ) -> Callable[[], "TpuSimTransport"]:
        """Zero-arg factory for ``Cluster.transport_factory(...)`` injection."""
        return lambda: self.transport(name)

    def set_default(self) -> None:
        """Make this bridge the target of the registered ``"tpusim"``
        transport factory, so a plain ``ClusterConfig`` with
        ``transport_factory="tpusim"`` resolves here like the tcp/websocket
        siblings resolve from their registries."""
        SimBridge._default = self

    # -- attach / detach (called by the endpoint lifecycle) ------------------
    def _attach(self, ep: "TpuSimTransport") -> None:
        row = self._d.join(self._seed_rows)
        ep.row = row
        ep._left = False
        ep._folded_inc = -1
        self._endpoints[ep.name] = ep
        # the endpoint IS the row's transport: sim-side user messaging to
        # this row (SimTransport.send peer lookup) reaches the real process
        self._d._transports[row] = ep
        if ep._identity is not None:
            # re-join after heal: the sim-side handle keeps the REAL identity
            self._d.members[row] = ep._identity
        stream = self._d.watch(row)
        ep._watch_unsub = stream.subscribe(
            lambda ev, _ep=ep: self._on_sim_event(_ep, ev)
        )

    def _detach(self, ep: "TpuSimTransport", crash: bool) -> None:
        if ep._watch_unsub is not None:
            ep._watch_unsub()
            ep._watch_unsub = None
        if ep.row is not None:
            if self._d._transports.get(ep.row) is ep:
                del self._d._transports[ep.row]
            if crash and not ep._left and self._d.is_up(ep.row):
                self._d.crash(ep.row)
        self._endpoints.pop(ep.name, None)

    # -- link chaos (the reconnect/backoff surface) --------------------------
    def link_up(self, ep: "TpuSimTransport") -> bool:
        return ep._link_up

    def fail_link(self, ep: "TpuSimTransport") -> None:
        """Sever a bridged process from the mesh mid-window: its sends start
        backing off, its window folds stop, and its row is crashed (the host
        mutation the next window realizes — to the sim it died)."""
        if not ep._link_up:
            return
        ep._link_up = False
        ep._emit_event("connection_lost", ep.address)
        if ep.row is not None and not ep._left and self._d.is_up(ep.row):
            self._d.crash(ep.row)

    def heal_link(self, ep: "TpuSimTransport") -> None:
        """Restore the link: the process re-joins on a fresh row (a restart
        is a new sim-side occupancy — the reference's rejoin-after-restart)
        and is handed the forced initial SYNC so its table catches up."""
        if ep._link_up:
            return
        if ep._watch_unsub is not None:
            ep._watch_unsub()
            ep._watch_unsub = None
        if ep.row is not None and self._d._transports.get(ep.row) is ep:
            del self._d._transports[ep.row]
        ep._link_up = True
        self._attach(ep)
        ep._emit_event("reconnected", ep.address)
        self.force_sync(ep)

    def force_sync(self, ep: "TpuSimTransport") -> None:
        """Push a full-table SYNC (seed row's view) into the endpoint — the
        same forced initial SYNC a fresh ``Cluster.start`` performs, minus
        the round trip."""
        records = self._sync_records(self._seed_rows[0], exclude=ep.address)
        msg = Message.with_data(
            SyncData(records),
            qualifier=Q_MEMBERSHIP_SYNC,
            sender=row_address(ep.row),
        )
        ep._deliver(msg)

    # -- real -> sim: routing ------------------------------------------------
    def deliver(self, src: "TpuSimTransport", address: str, message: Message) -> None:
        stamped = message.with_header(HEADER_SENDER, src.address)
        if address.startswith(BRIDGE_SCHEME):
            peer = self._endpoints.get(address[len(BRIDGE_SCHEME):])
            if (
                peer is None
                or peer.is_stopped
                or not peer._link_up
                or peer.row is None
                or not self._d.is_up(peer.row)
            ):
                return  # fire-and-forget drop, like a lost datagram
            peer._deliver(stamped)
        elif address.startswith("sim://"):
            self._proxy(src, _parse_sim_row(address), stamped)
        else:
            raise TransportError(f"not a bridged address: {address}")

    # -- real -> sim: the proxy plane ---------------------------------------
    def _proxy(self, src: "TpuSimTransport", row: int, msg: Message) -> None:
        q = msg.qualifier
        if q == Q_PING:
            self._on_ping(src, row, msg)
        elif q == Q_PING_REQ:
            self._on_ping_req(src, row, msg)
        elif q == Q_MEMBERSHIP_SYNC:
            self._on_sync(src, row, msg)
        elif q == Q_MEMBERSHIP_SYNC_ACK:
            self._fold_records(src, msg.data.membership)
        elif q == Q_METADATA_REQ:
            self._on_metadata(src, row, msg)
        elif q == Q_GOSSIP_REQ:
            self._on_gossip(src, row, msg)
        # anything else (user messages to a plain sim row) is consumed by the
        # simulated member — which has no user-level handler — silently, the
        # same as SimTransport delivery to a row nobody listens on.

    def _reply(self, src: "TpuSimTransport", row: int, msg: Message,
               reply: Message) -> None:
        reply = reply.with_header(HEADER_SENDER, row_address(row))
        if msg.correlation_id is not None:
            reply = reply.with_header(HEADER_CORRELATION_ID, msg.correlation_id)
        src._deliver(reply)

    def _on_ping(self, src: "TpuSimTransport", row: int, msg: Message) -> None:
        if not self._d.is_up(row):
            return  # silence -> caller's timeout -> SUSPECT
        data: PingData = msg.data
        occupant = self._d._member_handle(row)
        ack_type = (
            AckType.DEST_OK if occupant.id == data.to_member.id
            else AckType.DEST_GONE
        )
        self._reply(src, row, msg, Message.with_data(
            data.with_ack_type(ack_type), qualifier=Q_PING_ACK,
        ))

    def _on_ping_req(self, src: "TpuSimTransport", row: int, msg: Message) -> None:
        if not self._d.is_up(row):
            return  # the relay itself is down
        data: PingData = msg.data
        # the proxy relay short-circuits the transit PING: the target's
        # reachability is host-visible truth, so answer what the reference
        # relay would have forwarded (FailureDetectorImpl.onPingReq /
        # onTransitPingAck:330-360)
        verdict = self._member_reachable(data.to_member)
        if verdict is None:
            return  # target silent -> issuer times out -> SUSPECT
        plain = PingData(data.from_member, data.to_member, ack_type=verdict)
        self._reply(src, row, msg, Message.with_data(plain, qualifier=Q_PING_ACK))

    def _member_reachable(self, member: Member) -> Optional[AckType]:
        """None = silence; DEST_OK / DEST_GONE mirror the reference acks."""
        addr = member.address
        if addr.startswith("sim://"):
            row = _parse_sim_row(addr)
            if not self._d.is_up(row):
                return None
            occupant = self._d._member_handle(row)
            return AckType.DEST_OK if occupant.id == member.id else AckType.DEST_GONE
        if addr.startswith(BRIDGE_SCHEME):
            ep = self._endpoints.get(addr[len(BRIDGE_SCHEME):])
            if ep is None or ep.is_stopped or not ep._link_up:
                return None
            ident = ep._identity
            if ident is not None and ident.id != member.id:
                return AckType.DEST_GONE
            return AckType.DEST_OK
        return None

    def _on_sync(self, src: "TpuSimTransport", row: int, msg: Message) -> None:
        # fold FIRST: the initial SYNC is where the endpoint's real identity
        # is adopted, and the reply below must already carry it
        self._fold_records(src, msg.data.membership)
        if not self._d.is_up(row):
            return
        records = self._sync_records(row)
        self._reply(src, row, msg, Message.with_data(
            SyncData(records), qualifier=Q_MEMBERSHIP_SYNC_ACK,
        ))

    def _on_metadata(self, src: "TpuSimTransport", row: int, msg: Message) -> None:
        if not self._d.is_up(row):
            return
        request: GetMetadataRequest = msg.data
        occupant = self._d._member_handle(row)
        if request.member.id != occupant.id:
            return  # reference answers only for its own id
        blob = self._codec.serialize({"sim_row": row, "member": occupant.id})
        self._reply(src, row, msg, Message.with_data(
            GetMetadataResponse(occupant, blob), qualifier=Q_METADATA_RESP,
        ))

    def _on_gossip(self, src: "TpuSimTransport", row: int, msg: Message) -> None:
        if not self._d.is_up(row) or src.row is None:
            return
        for g in msg.data.gossips:
            if g.gossip_id in self._seen_gossip:
                continue
            self._seen_gossip[g.gossip_id] = True
            while len(self._seen_gossip) > 4096:
                self._seen_gossip.popitem(last=False)
            inner: Message = g.message
            if inner.qualifier == Q_MEMBERSHIP_GOSSIP:
                self._fold_records(src, [inner.data])
            elif self._d.is_up(src.row):
                # user gossip enters the simulated rumor plane at the
                # bridged row — the same spreadGossip seam scripted chaos uses
                self._d.spread_rumor(src.row, inner)

    # -- folding real-member state into the sim ------------------------------
    def _fold_records(self, src: "TpuSimTransport",
                      records: List[MembershipRecord]) -> None:
        """Fold the SENDER's own record into the driver. Records about sim
        members echo the sim's own state back (ignored — the device planes
        are authoritative), and records about OTHER bridged members are
        ignored too: bridged liveness is authored by the bridge link state,
        not by third-party gossip (SERVING.md § deviations)."""
        if src.row is None:
            return
        for rec in records:
            if rec.member.address != src.address:
                continue
            if src._identity is None or src._identity.id != rec.member.id:
                src._identity = rec.member
                self._d.members[src.row] = rec.member
            if rec.is_leaving and not src._left:
                src._left = True
                self._d.leave(src.row)
            elif rec.incarnation > src._folded_inc >= 0 and not src._left:
                # incarnation bump (refutation / metadata update) becomes a
                # sim-side inc bump so the mega-membership re-disseminates it
                self._d.update_metadata(src.row)
            src._folded_inc = max(src._folded_inc, rec.incarnation)

    # -- sim view -> records -------------------------------------------------
    def _sync_records(self, row: int, exclude: Optional[str] = None
                      ) -> List[MembershipRecord]:
        """Synthesize a full SyncData table from ``view_of(row)`` — one
        coalesced readback, same cost class as a /metrics scrape."""
        status, inc = self._d.view_of(row)
        records: List[MembershipRecord] = []
        # live-ish records only (reference SYNC tables drop DEAD); status can
        # also be the kernel's UNKNOWN sentinel (> DEAD) for forgotten rows
        for j in np.nonzero((status >= 0) & (status < MemberStatus.DEAD))[0]:
            j = int(j)
            st = MemberStatus(int(status[j]))
            member = self._d._member_handle(j)
            if exclude is not None and member.address == exclude:
                continue
            records.append(MembershipRecord(member, st, int(inc[j])))
        return records

    # -- sim -> real: window-boundary fold -----------------------------------
    def _on_sim_event(self, ep: "TpuSimTransport", ev) -> None:
        """Runs inside the driver step (possibly another thread, driver lock
        held): never touch the driver here — just stage the event and poke
        the endpoint's loop once per burst."""
        if not ep._link_up or ep.is_stopped:
            return
        if ev.member.address == ep.address:
            return  # the endpoint's own row: the real process owns its record
        ep._pending_events.append(ev)
        if ep._loop is not None and not ep._flush_scheduled:
            ep._flush_scheduled = True
            try:
                ep._loop.call_soon_threadsafe(self._flush_events, ep)
            except RuntimeError:
                ep._flush_scheduled = False  # loop closed mid-shutdown

    def _flush_events(self, ep: "TpuSimTransport") -> None:
        ep._flush_scheduled = False
        pending, ep._pending_events = ep._pending_events, []
        if not pending or ep.is_stopped or not ep._link_up or ep.row is None:
            return
        watch = self._d._watches.get(ep.row)
        key = watch.prev_key if watch is not None else None
        records: "OrderedDict[str, MembershipRecord]" = OrderedDict()
        for ev in pending:
            rec = self._event_record(ev, key)
            if rec is not None:
                records[rec.member.id] = rec  # last write per member wins
        if not records:
            return
        # ONE SyncData per window burst: merged by the ordinary serial
        # _sync_membership path, whose per-record fetch_metadata gate and
        # overrides lattice do the rest
        msg = Message.with_data(
            SyncData(list(records.values())),
            qualifier=Q_MEMBERSHIP_SYNC,
            sender=row_address(ep.row),
        )
        ep._deliver(msg)

    def _event_record(self, ev, key) -> Optional[MembershipRecord]:
        """Record for a watch event, status + incarnation lifted from the
        post-window key snapshot (no extra device readback)."""
        if ev.is_removed:
            return MembershipRecord(ev.member, MemberStatus.DEAD, 0)
        addr = ev.member.address
        if addr.startswith("sim://"):
            row = _parse_sim_row(addr)
        elif addr.startswith(BRIDGE_SCHEME):
            peer = self._endpoints.get(addr[len(BRIDGE_SCHEME):])
            if peer is None or peer.row is None:
                return None
            row = peer.row
        else:
            return None
        if key is None or row >= len(key):
            return None
        k = int(key[row])
        st = _status_of_key(k)
        if st not in (
            MemberStatus.ALIVE, MemberStatus.SUSPECT, MemberStatus.LEAVING,
        ):
            return MembershipRecord(ev.member, _GONE, 0)
        inc = (k >> 2) & self._d._lay.inc_mask
        return MembershipRecord(ev.member, MemberStatus(st), int(inc))


class TpuSimTransport(Transport):
    """One real process's endpoint on the bridge (``tpusim://<name>``).

    Same 4-method contract as the tcp/websocket siblings, including their
    bounded reconnect/backoff envelope: while the bridge link is severed,
    ``send`` retries with exponential backoff + jitter up to
    ``config.reconnect_max_retries``, emitting ``reconnect_backoff`` /
    ``reconnect_giveup`` on :meth:`transport_events` exactly like
    ``stream_base`` — churn monitoring sees bridge give-ups without
    scraping logs.
    """

    def __init__(self, bridge: SimBridge, name: str,
                 config: Optional[TransportConfig] = None) -> None:
        self._bridge = bridge
        self.name = name
        self._config = config or TransportConfig()
        self._listeners = Listeners()
        self._events: Listeners = Listeners()
        # fresh endpoints are NOT stopped (Cluster.start refuses a stopped
        # injected transport); "unstarted" is signaled by the address probe
        self._stopped = False
        self._started = False
        self.row: Optional[int] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._link_up = True
        self._identity: Optional[Member] = None
        self._folded_inc = -1
        self._left = False
        self._watch_unsub: Optional[Callable[[], None]] = None
        self._pending_events: list = []
        self._flush_scheduled = False

    # -- Transport contract --------------------------------------------------
    @property
    def address(self) -> str:
        if not self._started:
            raise TransportError("transport is not started")
        return f"{BRIDGE_SCHEME}{self.name}"

    @property
    def is_stopped(self) -> bool:
        return self._stopped

    async def start(self) -> "TpuSimTransport":
        if self._started and not self._stopped:
            return self
        self._loop = asyncio.get_running_loop()
        self._bridge._attach(self)
        self._started = True
        self._stopped = False
        self._link_up = True
        return self

    async def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        # graceful LEAVING was folded already (if the cluster left); an
        # abrupt stop crashes the row — to the sim the process died
        self._bridge._detach(self, crash=True)

    def listen(self) -> Listeners:
        return self._listeners

    def transport_events(self) -> Listeners:
        return self._events

    async def send(self, address: str, message: Message) -> None:
        if self._stopped:
            raise TransportError("transport is stopped")
        attempt = 0
        while True:
            if self._bridge.link_up(self):
                self._bridge.deliver(self, address, message)
                return
            attempt += 1
            if self._stopped or attempt > self._config.reconnect_max_retries:
                self._emit_event(
                    "reconnect_giveup", address, attempts=attempt,
                    error="bridge link down",
                )
                raise PeerUnavailableError(
                    f"send to {address} failed after {attempt} attempt(s): "
                    "bridge link down"
                )
            delay = self._backoff_delay(attempt)
            self._emit_event(
                "reconnect_backoff", address, attempts=attempt, delay=delay,
            )
            await asyncio.sleep(delay)

    # -- internals -----------------------------------------------------------
    def _backoff_delay(self, attempt: int) -> float:
        base = self._config.reconnect_base_delay * (2 ** (attempt - 1))
        return min(base, self._config.reconnect_max_delay) * (
            0.5 + random.random()
        )

    def _emit_event(self, kind: str, address: str, **fields) -> None:
        self._events.emit(TransportEvent(kind=kind, address=address, **fields))

    def _deliver(self, message: Message) -> None:
        """Inject a message into this endpoint's listen stream on its loop
        (thread-safe: window folds may originate in a stepping thread)."""
        if self._stopped or self._loop is None:
            return
        try:
            if self._loop is _running_loop():
                self._loop.call_soon(self._listeners.emit, message)
            else:
                self._loop.call_soon_threadsafe(self._listeners.emit, message)
        except RuntimeError:
            pass  # loop closed during shutdown


def _running_loop() -> Optional[asyncio.AbstractEventLoop]:
    try:
        return asyncio.get_running_loop()
    except RuntimeError:
        return None


def _tpusim_factory(config: TransportConfig) -> TpuSimTransport:
    bridge = SimBridge._default
    if bridge is None:
        raise TransportError(
            "transport_factory='tpusim' needs a default bridge: build a "
            "SimBridge(driver) and call bridge.set_default() first (or "
            "inject with Cluster.transport_factory(bridge.transport_factory()))"
        )
    return bridge.transport(config=config)


register_transport_factory("tpusim", _tpusim_factory)
