"""Operator load generator for the hybrid serving stack (r19).

Drives two traffic planes concurrently against a live hybrid cluster:

* **member-facing churn** — sustained ``join`` / ``leave`` /
  ``update_metadata`` / ``spread_rumor`` host mutations through the
  driver's public seam (the same calls the bridge proxy folds real-member
  traffic into), each op individually wall-clocked;
* **scrape traffic** — concurrent ``/metrics`` + ``/trace`` + ``/whatif``
  HTTP GETs against a live :class:`~scalecube_cluster_tpu.monitor.MonitorServer`
  over raw asyncio sockets (no client library), each scrape wall-clocked.

A stepping task keeps the simulated windows advancing at a fixed cadence
while the load runs, so ops land in real windows and scrapes observe a
moving membership — serving and simulation contend exactly as they would
in production. Latency histograms (p50/p90/p99/max) are computed per op
kind and per scrape path; when the driver's telemetry bus is armed the
summary is also published as a ``("loadgen", "summary")`` bus record, so
the existing `/metrics`-adjacent tooling sees the run without a side
channel.
"""

from __future__ import annotations

import asyncio
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np


def _percentiles(samples: List[float]) -> Dict[str, float]:
    if not samples:
        return {"count": 0}
    arr = np.asarray(samples) * 1e3  # ms
    return {
        "count": len(samples),
        "p50_ms": round(float(np.percentile(arr, 50)), 3),
        "p90_ms": round(float(np.percentile(arr, 90)), 3),
        "p99_ms": round(float(np.percentile(arr, 99)), 3),
        "max_ms": round(float(arr.max()), 3),
    }


@dataclass
class LoadReport:
    """Outcome of one :meth:`LoadGenerator.run` — JSON-able as-is."""

    duration_s: float = 0.0
    ops: int = 0
    ops_per_s: float = 0.0
    op_latency: Dict[str, Dict[str, float]] = field(default_factory=dict)
    scrapes: Dict[str, Dict[str, float]] = field(default_factory=dict)
    scrape_errors: int = 0
    op_errors: int = 0
    windows_stepped: int = 0

    def as_dict(self) -> dict:
        return {
            "duration_s": round(self.duration_s, 3),
            "ops": self.ops,
            "ops_per_s": round(self.ops_per_s, 1),
            "op_latency": self.op_latency,
            "scrapes": self.scrapes,
            "scrape_errors": self.scrape_errors,
            "op_errors": self.op_errors,
            "windows_stepped": self.windows_stepped,
        }


class LoadGenerator:
    """Churn + scrape load against a driver (and optionally a monitor)."""

    def __init__(
        self,
        driver,
        *,
        monitor_url: Optional[str] = None,
        seed: int = 0,
        seed_rows: Sequence[int] = (0,),
        max_churn_pool: int = 32,
    ) -> None:
        self._d = driver
        self._monitor_url = monitor_url
        self._rng = random.Random(seed)
        self._seed_rows = tuple(seed_rows)
        self._max_pool = max_churn_pool
        self._pool: List[int] = []  # rows this generator joined and may leave
        # churn ops run on executor threads (a driver mutator may wait out
        # a whole in-flight window on the driver lock — parking that wait
        # on the event loop would starve the scrape lanes); the pool list
        # needs its own lock there
        self._pool_lock = threading.Lock()

    # -- churn ---------------------------------------------------------------
    #: metadata bumps arrive batched (operator consoles coalesce them into
    #: one dispatch); the fori_loop batch is launch-dominated, so a wide
    #: batch serves ~linearly more member ops per dispatch slot. Rumors are
    #: broadcasts — rare relative to the rest of the mix, so the bounded
    #: slot pool recycles instead of thrashing
    METADATA_BATCH = 32

    def _one_op(self, lat: Dict[str, List[float]]) -> int:
        """One member-facing dispatch; returns how many member ops it
        served (a metadata batch counts each row), 0 on a refusal."""
        d = self._d
        with self._pool_lock:
            kind = self._rng.choices(
                ("metadata", "rumor", "join", "leave"),
                weights=(0.70, 0.05, 0.125, 0.125),
            )[0]
            if kind == "leave" and not self._pool:
                kind = "join"
            if kind == "join" and len(self._pool) >= self._max_pool:
                kind = "leave"
            pick = tuple(self._pool) if self._pool else self._seed_rows
            leave_row = (
                self._pool.pop(self._rng.randrange(len(self._pool)))
                if kind == "leave" else -1
            )
            rows = [
                self._rng.choice(pick) for _ in range(self.METADATA_BATCH)
            ] if kind == "metadata" else ()
        served = 1
        t0 = time.perf_counter()
        try:
            if kind == "metadata":
                d.update_metadata_batch(rows)
                served = len(rows)
            elif kind == "rumor":
                d.spread_rumor(self._rng.choice(pick), {"loadgen": True})
            elif kind == "join":
                joined = d.join(self._seed_rows)
                with self._pool_lock:
                    self._pool.append(joined)
            else:
                d.leave(leave_row)
        except RuntimeError:
            # capacity / rumor-slot exhaustion under extreme churn is a
            # refusal, not a crash — counted, never fatal
            return 0
        lat.setdefault(kind, []).append(time.perf_counter() - t0)
        return served

    async def _churn_worker(self, deadline: float, report: LoadReport,
                            lat: Dict[str, List[float]]) -> None:
        loop = asyncio.get_running_loop()
        while time.perf_counter() < deadline:
            # executor thread: the op may park on the driver lock behind a
            # stepping window; the event loop keeps serving scrapes
            served = await loop.run_in_executor(None, self._one_op, lat)
            if served:
                report.ops += served
            else:
                report.op_errors += 1

    # -- scrapes -------------------------------------------------------------
    async def _scrape_once(self, path: str) -> float:
        assert self._monitor_url is not None
        hostport = self._monitor_url.split("://", 1)[1]
        host, _, port = hostport.rpartition(":")
        t0 = time.perf_counter()
        reader, writer = await asyncio.open_connection(host, int(port))
        writer.write(
            f"GET {path} HTTP/1.1\r\nHost: {host}\r\n"
            "Connection: close\r\n\r\n".encode()
        )
        await writer.drain()
        payload = await reader.read(-1)
        writer.close()
        if b" 200 " not in payload.split(b"\r\n", 1)[0]:
            raise RuntimeError(f"scrape {path}: non-200")
        return time.perf_counter() - t0

    async def _scrape_worker(self, deadline: float, paths: Sequence[str],
                             report: LoadReport,
                             lat: Dict[str, List[float]]) -> None:
        i = 0
        while time.perf_counter() < deadline:
            path = paths[i % len(paths)]
            i += 1
            try:
                lat.setdefault(path, []).append(await self._scrape_once(path))
            except (OSError, RuntimeError, asyncio.IncompleteReadError):
                report.scrape_errors += 1
            await asyncio.sleep(0)

    # -- warmup --------------------------------------------------------------
    async def warmup(
        self,
        scrape_paths: Sequence[str] = ("/metrics", "/trace", "/whatif"),
        step_window: int = 2,
    ) -> None:
        """One untimed pass over every lane before the clock starts.

        Each op kind fires once, one window steps, and each scrape path is
        hit once — so first-call jit compiles (the driver caches one jitted
        program per mutator and per window size) and connection setup land
        here instead of inside the measured run. Skipping this is valid but
        measures cold-start, not steady-state serving.
        """
        d = self._d
        d.update_metadata(self._seed_rows[0])
        d.update_metadata_batch([self._seed_rows[0]] * self.METADATA_BATCH)
        d.spread_rumor(self._seed_rows[0], {"warmup": True})
        d.leave(d.join(self._seed_rows))
        d.step(step_window)
        if self._monitor_url is not None:
            for path in scrape_paths:
                try:
                    await self._scrape_once(path)
                except (OSError, RuntimeError, asyncio.IncompleteReadError):
                    pass  # timed run will surface real scrape failures

    # -- stepping ------------------------------------------------------------
    async def _stepper(self, deadline: float, report: LoadReport,
                       window: int, interval_s: float) -> None:
        loop = asyncio.get_running_loop()
        while time.perf_counter() < deadline:
            # executor thread: the window holds the driver lock for its
            # whole compute — ops queue behind it (real contention, kept),
            # but the event loop stays free to serve scrapes
            await loop.run_in_executor(None, self._d.step, window)
            report.windows_stepped += 1
            await asyncio.sleep(interval_s)

    # -- entry ---------------------------------------------------------------
    async def run(
        self,
        duration_s: float = 2.0,
        *,
        churn_workers: int = 2,
        scrape_workers: int = 2,
        scrape_paths: Sequence[str] = ("/metrics", "/trace", "/whatif"),
        step_window: int = 2,
        step_interval_s: float = 0.2,
    ) -> LoadReport:
        report = LoadReport()
        op_lat: Dict[str, List[float]] = {}
        scrape_lat: Dict[str, List[float]] = {}
        t0 = time.perf_counter()
        deadline = t0 + duration_s
        tasks = [
            self._churn_worker(deadline, report, op_lat)
            for _ in range(churn_workers)
        ]
        tasks.append(self._stepper(deadline, report, step_window, step_interval_s))
        if self._monitor_url is not None and scrape_workers > 0:
            tasks.extend(
                self._scrape_worker(deadline, scrape_paths, report, scrape_lat)
                for _ in range(scrape_workers)
            )
        await asyncio.gather(*tasks)
        report.duration_s = time.perf_counter() - t0
        report.ops_per_s = report.ops / max(report.duration_s, 1e-9)
        report.op_latency = {k: _percentiles(v) for k, v in op_lat.items()}
        report.scrapes = {k: _percentiles(v) for k, v in scrape_lat.items()}
        # surface through the armed telemetry plane (bus record), if any
        try:
            self._d._publish(
                "loadgen", "summary", ops=report.ops,
                ops_per_s=round(report.ops_per_s, 1),
                scrape_errors=report.scrape_errors,
            )
        except Exception:
            pass  # bus not armed — the returned report is the artifact
        return report
