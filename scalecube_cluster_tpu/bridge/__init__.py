"""Hybrid serving bridge (r19): real processes inside the simulated mesh.

``SimBridge`` splices real ``Cluster`` processes into a live ``SimDriver``
membership over ``TpuSimTransport`` (a registered ``"tpusim"`` sibling of
the tcp/websocket transports); ``LoadGenerator`` drives member-facing churn
and monitor scrape traffic against the hybrid. See ``docs/SERVING.md``.
"""

from .transport import BRIDGE_SCHEME, BridgeError, SimBridge, TpuSimTransport
from .loadgen import LoadGenerator, LoadReport

__all__ = [
    "BRIDGE_SCHEME",
    "BridgeError",
    "SimBridge",
    "TpuSimTransport",
    "LoadGenerator",
    "LoadReport",
]
