"""Static program-audit plane (r12).

Proves the repo's load-bearing invariants over each engine's COMPILED
window programs — closed jaxprs, lowered StableHLO, AOT-compiled HLO and
its ``memory_analysis`` — instead of sampling them from runs or matching
source text:

* r6 donated-buffer aliasing (every donated leaf aliased, no stale escape),
* r6/r8/r10 transfer-freeness (no host callback/infeed/outfeed primitive),
* the r10 in-scan wide-plane materialization pattern (~18%/tick),
* the r11 pview O(N·k) no-wide-value guarantee,
* the r9/r11 per-engine window memory budgets,
* the r6 ``restore()`` copy rule, via each engine's registered
  ``restore_module`` (AST lint through the contract registry).

Contracts are declared per engine on
:class:`..ops.engine_api.EngineContracts`; ``tools/audit_programs.py`` is
the CLI; ``tests/test_audit_programs.py`` runs the fast matrix in tier-1
and falsifiability-tests every contract class on seeded violations.
"""

from .contracts import (
    CHECKERS,
    TRANSFER_PRIMITIVES,
    Violation,
    check_donation_alias,
    check_forbid_wide_values,
    check_memory_budget,
    check_no_plane_materialization,
    check_restore_seams,
    check_transfer_free,
    run_contracts,
)
from .programs import (
    DEFAULT_CAPACITY,
    DEFAULT_N_TICKS,
    DEFAULT_SHARDED_CAPACITY,
    AuditProgram,
    build_engine_programs,
    build_matrix,
)
from .report import audit_all, audit_programs, format_text

__all__ = [
    "AuditProgram",
    "CHECKERS",
    "DEFAULT_CAPACITY",
    "DEFAULT_N_TICKS",
    "DEFAULT_SHARDED_CAPACITY",
    "TRANSFER_PRIMITIVES",
    "Violation",
    "audit_all",
    "audit_programs",
    "build_engine_programs",
    "build_matrix",
    "check_donation_alias",
    "check_forbid_wide_values",
    "check_memory_budget",
    "check_no_plane_materialization",
    "check_restore_seams",
    "check_transfer_free",
    "format_text",
    "run_contracts",
]
