"""Verdict assembly: one machine-readable artifact per audit run.

The JSON shape (``AUDIT_r12.json``, also folded into the bench collector's
round artifact) is deliberately boring — a flat program list with per-
contract verdicts — so CI can diff it and the COMPILE_PROOF family of
artifacts can absorb it without schema gymnastics.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

from . import contracts as C
from .programs import AuditProgram, build_matrix

SCHEMA = 1


def audit_programs(
    programs: Sequence[AuditProgram],
    compile_programs: bool = True,
    engine_names: Optional[Sequence[str]] = None,
    restore_seams: bool = True,
) -> Dict:
    """Run every applicable contract over ``programs`` and assemble the
    verdict dict. ``compile_programs=False`` audits traced/lowered forms
    only (no AOT compile, no memory figures) — the fast tier-1 mode."""
    import jax

    t0 = time.perf_counter()
    entries: List[Dict] = []
    n_violations = 0
    for prog in programs:
        per_contract = C.run_contracts(prog, compile_programs)
        entry = {
            "program": prog.name,
            "engine": prog.engine,
            "variant": prog.variant,
            "key_dtype": prog.key_dtype,
            "capacity": prog.capacity,
            "n_ticks": prog.n_ticks,
            "mesh_size": prog.mesh_size,
            "donated_leaves": len(prog.donated_leaf_info()),
            "budget_basis_bytes": prog.budget_basis_bytes,
            "contracts": {},
        }
        for name, violations in per_contract.items():
            entry["contracts"][name] = {
                "ok": not violations,
                "violations": [
                    {"message": v.message, "where": v.where}
                    for v in violations
                ],
            }
            n_violations += len(violations)
        if compile_programs:
            entry["memory"] = prog.memory()
            entry["memory"]["budget_bytes"] = int(
                prog.contracts.memory_factor * prog.budget_basis_bytes
                + prog.contracts.memory_overhead_mib * (1 << 20)
            )
        entries.append(entry)

    seam_violations: List[C.Violation] = []
    if restore_seams:
        seam_violations = C.check_restore_seams(engine_names)
        n_violations += len(seam_violations)

    return {
        "schema": SCHEMA,
        "generated_by": "scalecube_cluster_tpu.audit",
        "jax_version": jax.__version__,
        "compiled": compile_programs,
        "elapsed_s": round(time.perf_counter() - t0, 2),
        "n_programs": len(entries),
        "n_violations": n_violations,
        "ok": n_violations == 0,
        "programs": entries,
        "restore_seams": {
            "checked": restore_seams,
            "ok": not seam_violations,
            "violations": [
                {"engine": v.program, "message": v.message, "where": v.where}
                for v in seam_violations
            ],
        },
    }


def audit_all(
    engines: Optional[Sequence[str]] = None,
    capacity: int = 128,
    n_ticks: int = 4,
    variants: Optional[Sequence[str]] = None,
    sharded_capacity: int = 256,
    compile_programs: bool = True,
) -> Dict:
    """Build the matrix and audit it — the CLI/test entry point."""
    programs = build_matrix(
        engines, capacity=capacity, n_ticks=n_ticks, variants=variants,
        sharded_capacity=sharded_capacity,
    )
    return audit_programs(
        programs, compile_programs=compile_programs, engine_names=engines
    )


def format_text(verdict: Dict) -> str:
    """Human rendering of one verdict dict (the CLI's default output)."""
    lines: List[str] = []
    ok = "PASS" if verdict["ok"] else "FAIL"
    lines.append(
        f"program audit: {ok} — {verdict['n_programs']} program(s), "
        f"{verdict['n_violations']} violation(s), "
        f"{verdict['elapsed_s']}s (jax {verdict['jax_version']}, "
        f"{'compiled' if verdict['compiled'] else 'lowered-only'})"
    )
    for entry in verdict["programs"]:
        marks = []
        for cname, c in entry["contracts"].items():
            marks.append(f"{cname}={'ok' if c['ok'] else 'VIOLATED'}")
        mem = entry.get("memory")
        memtxt = (
            f" peak={mem['peak_live_bytes']}B/budget={mem['budget_bytes']}B"
            if mem else ""
        )
        lines.append(f"  {entry['program']}: {' '.join(marks)}{memtxt}")
        for cname, c in entry["contracts"].items():
            for v in c["violations"]:
                where = f" [{v['where']}]" if v["where"] else ""
                lines.append(f"    ! {cname}: {v['message']}{where}")
    seams = verdict["restore_seams"]
    if seams["checked"]:
        lines.append(
            f"  restore seams: {'ok' if seams['ok'] else 'VIOLATED'}"
        )
        for v in seams["violations"]:
            lines.append(
                f"    ! {v['engine']}: {v['message']} [{v['where']}]"
            )
    return "\n".join(lines)
