"""Shared jaxpr traversal for the static audit plane (r12).

Every contract checker in :mod:`.contracts` walks the CLOSED jaxpr of a
window program — including every sub-jaxpr a primitive carries in its
params (scan bodies, cond branches, pjit calls, custom_jvp wrappers) —
so nothing a decorator or helper function hides from a source regex can
hide from the audit. This module is the one spelling of that traversal,
plus the source-provenance summarizer findings use to name the offending
equation's origin line.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple


def sub_jaxprs(eqn) -> Iterator:
    """Every jaxpr carried in one equation's params (scan/cond/pjit/...),
    unwrapped from ClosedJaxpr when needed."""
    for v in eqn.params.values():
        for sub in v if isinstance(v, (list, tuple)) else [v]:
            tn = type(sub).__name__
            if tn == "ClosedJaxpr":
                yield sub.jaxpr
            elif tn == "Jaxpr":
                yield sub


def walk_eqns(jaxpr, depth: int = 0) -> Iterator[Tuple[object, int]]:
    """Depth-first over every equation at every nesting level."""
    for eqn in jaxpr.eqns:
        yield eqn, depth
        for sj in sub_jaxprs(eqn):
            yield from walk_eqns(sj, depth + 1)


def outer_scans(jaxpr, in_scan: bool = False) -> Iterator:
    """The scan equations NOT nested inside another scan — the window
    loops whose ys are the per-tick stacked outputs. Sub-scans inside a
    tick (samplers, merge sweeps) are deliberately excluded: their ys feed
    the tick computation, so the ys-only escape analysis does not apply to
    them."""
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            if not in_scan:
                yield eqn
            for sj in sub_jaxprs(eqn):
                yield from outer_scans(sj, True)
        else:
            for sj in sub_jaxprs(eqn):
                yield from outer_scans(sj, in_scan)


def is_var(v) -> bool:
    """True for a jaxpr Var (Literals and DropVars carry no dataflow)."""
    return type(v).__name__ == "Var"


def var_avals(eqn) -> Iterator:
    for v in eqn.invars:
        if is_var(v):
            yield v.aval


def provenance(eqn) -> str:
    """``file:line (function)`` of the traced source that emitted this
    equation — the actionable pointer every finding carries. Private-API
    tolerant: falls back to the primitive name if jax moves the helper."""
    try:
        from jax._src import source_info_util

        s = source_info_util.summarize(eqn.source_info)
        return s if s else f"<{eqn.primitive.name}>"
    except Exception:  # pragma: no cover - jax internals moved
        return f"<{eqn.primitive.name}>"


def count_wide_dims(aval, threshold: int) -> int:
    """How many dims of ``aval`` are >= ``threshold`` (the capacity-scaled
    width test — audit params guarantee every non-capacity dim is smaller
    than capacity, see programs.build_matrix)."""
    return sum(1 for d in getattr(aval, "shape", ()) if d >= threshold)


def is_wide(aval, threshold: int) -> bool:
    """A capacity²-proportional value: >= 2 dims each >= capacity."""
    return count_wide_dims(aval, threshold) >= 2


def find_wide_gather(eqn, threshold: int) -> Optional[object]:
    """The first gather/dynamic_slice equation inside ``eqn`` (itself or
    any sub-jaxpr — a cond branch hides nothing) that CONSUMES a wide
    plane; None when there is none."""
    if eqn.primitive.name in ("gather", "dynamic_slice"):
        if any(is_wide(a, threshold) for a in var_avals(eqn)):
            return eqn
    for sj in sub_jaxprs(eqn):
        for sub in sj.eqns:
            hit = find_wide_gather(sub, threshold)
            if hit is not None:
                return hit
    return None
