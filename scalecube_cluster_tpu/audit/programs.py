"""The audit matrix: every engine's window programs as inspectable objects.

One :class:`AuditProgram` wraps one jitted program the driver actually
dispatches — a window builder from the :class:`..ops.engine_api.EngineOps`
descriptor (unarmed, trace-armed, mesh-sharded) or a telemetry-plane
device program (the metric-ring row reduction and its donated append) —
together with the ABSTRACT arguments it is lowered against
(``jax.ShapeDtypeStruct`` leaves; a mesh run carries ``NamedSharding``)
and the bookkeeping the contract checkers need: which flattened argument
positions are donated, what one copy of the donatable state weighs
per device, and which capacity value makes a dimension "wide".

Nothing here executes a tick: programs are traced (``jax.make_jaxpr``),
lowered (``.lower()`` → StableHLO), and optionally AOT-compiled
(``.compile()`` → optimized HLO + ``memory_analysis``) on abstract inputs
only, so the full matrix audits in seconds and the same code can audit a
million-member pview program without allocating it.

Audit-shape precondition: every sizing knob that is NOT the member
capacity (rumor/pool/announce slots, trace ring length and field count)
is kept STRICTLY below ``capacity`` by :func:`build_matrix`, so "dim >=
capacity" is exactly "capacity-scaled dim" for the wide-plane checks.
:func:`build_matrix` asserts this rather than trusting it.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..ops import engine_api

#: ticks per audited window — small keeps compiles fast; every contract is
#: tick-count-invariant (the checks run on the scan BODY / whole jaxpr)
DEFAULT_N_TICKS = 4
DEFAULT_CAPACITY = 128
#: sharded runs need capacity % (32 * mesh.size) == 0 (the r9/r11 word rule)
DEFAULT_SHARDED_CAPACITY = 256
#: scenario-axis length of the r15 fleet audit shapes — small keeps the
#: vmapped compile fast, and every fleet contract is S-invariant (donation
#: covers the whole stacked pytree, the memory budget is declared
#: per-scenario × S, wide-plane checks key on capacity-scaled dims so the
#: S dim must stay strictly below capacity — asserted at build)
DEFAULT_FLEET_SCENARIOS = 4

MIB = 1 << 20


def _abstract(tree, shardings=None):
    if shardings is None:
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree
        )
    return jax.tree.map(
        lambda x, sh: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh),
        tree,
        shardings,
    )


def _leaf_paths(tree) -> List[str]:
    paths, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [jax.tree_util.keystr(kp) for kp, _ in paths]


def _tree_bytes(tree, per_device: bool = False) -> int:
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = leaf.shape
        sharding = getattr(leaf, "sharding", None)
        if per_device and sharding is not None:
            try:
                shape = sharding.shard_shape(shape)
            except Exception:  # replicated / abstract corner: full copy
                pass
        n = 1
        for d in shape:
            n *= d
        total += n * leaf.dtype.itemsize
    return total


@dataclasses.dataclass
class AuditProgram:
    """One compiled-surface claim: a jitted program + its audit metadata."""

    name: str  # e.g. "dense/i32/unarmed"
    engine: str  # "dense" | "sparse" | "pview" | plane programs keep owner
    variant: str  # "unarmed" | "traced" | "sharded" | "telemetry-row" | ...
    key_dtype: str
    capacity: int
    n_ticks: int
    fn: Callable  # the jitted callable (positional args only)
    abstract_args: Tuple  # ShapeDtypeStruct pytrees, positionally
    donated_argnums: Tuple[int, ...]
    contracts: engine_api.EngineContracts
    #: denominator of the memory budget: one copy of the donatable state
    #: (plus ring, for armed programs), PER DEVICE for sharded programs
    budget_basis_bytes: int
    #: dims >= this are capacity-scaled (see module docstring precondition)
    wide_threshold: int
    #: whether the scan-materialization / forbid-wide checks apply (window
    #: programs; the telemetry row/append programs hold no tick scan)
    is_window: bool = True
    mesh_size: int = 1

    # -- cached derived forms -------------------------------------------------
    _closed = None
    _lowered = None
    _compiled = None

    @property
    def closed_jaxpr(self):
        if self._closed is None:
            fn = self.fn
            self._closed = jax.make_jaxpr(lambda *a: fn(*a))(
                *self.abstract_args
            )
        return self._closed

    @property
    def lowered(self):
        if self._lowered is None:
            self._lowered = self.fn.lower(*self.abstract_args)
        return self._lowered

    @property
    def mlir_text(self) -> str:
        return self.lowered.as_text()

    def compiled(self):
        if self._compiled is None:
            self._compiled = self.lowered.compile()
        return self._compiled

    def memory(self) -> Dict[str, int]:
        """XLA ``memory_analysis`` of the compiled program (per-device
        figures for an SPMD module) + the derived peak-live bytes."""
        ma = self.compiled().memory_analysis()
        out: Dict[str, int] = {}
        for field in (
            "argument_size_in_bytes",
            "output_size_in_bytes",
            "temp_size_in_bytes",
            "alias_size_in_bytes",
            "generated_code_size_in_bytes",
        ):
            v = getattr(ma, field, None)
            if v is not None:
                out[field] = int(v)
        out["peak_live_bytes"] = (
            out.get("argument_size_in_bytes", 0)
            + out.get("output_size_in_bytes", 0)
            + out.get("temp_size_in_bytes", 0)
            - out.get("alias_size_in_bytes", 0)
        )
        return out

    # -- donation bookkeeping -------------------------------------------------

    def donated_leaf_info(self) -> List[Tuple[int, str, int]]:
        """(flat arg position, leaf path, byte size) of every leaf of every
        donated argument — the positions the alias map must cover."""
        out: List[Tuple[int, str, int]] = []
        pos = 0
        for i, arg in enumerate(self.abstract_args):
            leaves = jax.tree.leaves(arg)
            if i in self.donated_argnums:
                paths = _leaf_paths(arg)
                for path, leaf in zip(paths, leaves):
                    n = 1
                    for d in leaf.shape:
                        n *= d
                    out.append((pos, f"arg{i}{path}", n * leaf.dtype.itemsize))
                    pos += 1
            else:
                pos += len(leaves)
        return out

    def flat_invars(self) -> list:
        return list(self.closed_jaxpr.jaxpr.invars)


def _assert_audit_shape(name: str, capacity: int, sizes: Dict[str, int]):
    """The build-time precondition that makes ``dim >= capacity`` mean
    ``capacity-scaled``: every non-capacity sizing knob strictly below
    capacity."""
    offenders = {k: v for k, v in sizes.items() if v >= capacity}
    if offenders:
        raise ValueError(
            f"audit matrix misconfigured for {name}: non-capacity dims "
            f"{offenders} are >= capacity {capacity}, so the wide-plane "
            "checks could not tell pools from planes — shrink the knobs or "
            "raise --capacity"
        )


def _audit_params(engine: str, capacity: int, key_dtype: str):
    """Small-but-real protocol params for the audit shapes (the N=128
    configs of ISSUE 7): bounded pools sized strictly below capacity."""
    if engine == "dense":
        from ..ops.state import SimParams

        p = SimParams(capacity=capacity, rumor_slots=16, key_dtype=key_dtype)
        sizes = {"rumor_slots": p.rumor_slots}
    elif engine == "sparse":
        from ..ops.sparse import SparseParams

        p = SparseParams(
            capacity=capacity, rumor_slots=16, mr_slots=capacity // 2,
            announce_slots=32,
        )
        sizes = {
            "rumor_slots": p.rumor_slots,
            "mr_slots": p.mr_slots,
            "announce_slots": p.announce_slots,
        }
    elif engine == "pview":
        from ..ops.pview import PviewParams

        p = PviewParams(
            capacity=capacity, rumor_slots=16, mr_slots=capacity // 2,
            announce_slots=32, key_dtype=key_dtype,
        )
        sizes = {
            "rumor_slots": p.rumor_slots,
            "mr_slots": p.mr_pool,
            "announce_slots": p.announce_slots,
            "view_slots": p.view_slots,
        }
    else:
        raise ValueError(f"unknown engine {engine!r}")
    _assert_audit_shape(f"{engine}/{key_dtype}", capacity, sizes)
    return p


def _trace_spec(capacity: int):
    from ..trace.schema import TraceSpec

    spec = TraceSpec(tracer_rows=(1, 2), rumor_slots=(0,), ring_len=64)
    _assert_audit_shape(
        "trace", capacity,
        {"ring_len": spec.ring_len, "n_fields": spec.n_fields},
    )
    return spec


def _key_abstract():
    k = jax.random.PRNGKey(0)
    return jax.ShapeDtypeStruct(k.shape, k.dtype)


def build_engine_programs(
    engine_name: str,
    capacity: int = DEFAULT_CAPACITY,
    n_ticks: int = DEFAULT_N_TICKS,
    key_dtypes: Optional[Sequence[str]] = None,
    variants: Optional[Sequence[str]] = None,
    sharded_capacity: int = DEFAULT_SHARDED_CAPACITY,
) -> List[AuditProgram]:
    """The audit programs of one engine: for each registered key dtype the
    unarmed window, and for the primary (i32) dtype the trace-armed window,
    the telemetry-plane device programs, and (where the engine supports a
    mesh) the sharded window over all local devices.

    ``variants`` filters to a subset ("unarmed", "traced", "telemetry",
    "sharded") — the fast tier-1 test audits unarmed+traced only.
    """
    eng = engine_api.engine(engine_name)
    contracts = eng.contracts
    dtypes = tuple(key_dtypes) if key_dtypes else contracts.key_dtypes
    want = set(variants) if variants else {
        "unarmed", "traced", "telemetry", "sharded", "strategy", "adaptive",
        "fleet", "control", "fused", "replay", "bridge",
    }
    key_abs = _key_abstract()
    programs: List[AuditProgram] = []

    for kd in dtypes:
        params = _audit_params(engine_name, capacity, kd)
        n_initial = max(2, (capacity * 3) // 4)
        state = eng.init_state(params, n_initial, True, eng.dense_links_default)
        abs_state = _abstract(state)
        state_bytes = _tree_bytes(abs_state)

        if "unarmed" in want:
            programs.append(AuditProgram(
                name=f"{engine_name}/{kd}/unarmed",
                engine=engine_name, variant="unarmed", key_dtype=kd,
                capacity=capacity, n_ticks=n_ticks,
                fn=eng.make_run(params, n_ticks),
                abstract_args=(abs_state, key_abs),
                donated_argnums=(0,),
                contracts=contracts,
                budget_basis_bytes=state_bytes,
                wide_threshold=capacity,
            ))

        if kd == dtypes[0] and "traced" in want:
            spec = _trace_spec(capacity)
            buf = jax.ShapeDtypeStruct((spec.ring_len, spec.n_fields), jnp.int32)
            cur = jax.ShapeDtypeStruct((), jnp.int32)
            programs.append(AuditProgram(
                name=f"{engine_name}/{kd}/traced",
                engine=engine_name, variant="traced", key_dtype=kd,
                capacity=capacity, n_ticks=n_ticks,
                fn=eng.make_traced_run(params, n_ticks, spec),
                abstract_args=(abs_state, key_abs, buf, cur),
                donated_argnums=(0, 2),
                contracts=contracts,
                budget_basis_bytes=state_bytes + _tree_bytes(buf),
                wide_threshold=capacity,
            ))

        if kd == dtypes[0] and "bridge" in want:
            # r19: the bridge-watched window — the EXACT program the driver
            # dispatches while TpuSimTransport endpoints hold armed watches
            # (watch_rows bound as a live [W] operand). The variant proves
            # the serving-path claims: donation still aliases the full
            # state, the watch plumbing smuggles in NO host transfer (the
            # bridge's real-member fold stays a host seam outside the jit),
            # and the budget covers the stacked [n_ticks, W, N] watched
            # keys. The one contract it must WAIVE on the wide-plane
            # engines is no_plane_materialization: the in-scan
            # view_key[watch_rows] gather IS the documented r10 opt-in a
            # watch costs (pinned as the seeded violation in
            # tests/test_audit_programs.py), so auditing it as a failure
            # would just re-find the known price. pview synthesizes watched
            # rows from O(N·k) state, so its checks (including the r11
            # wide-value ban) all stay live.
            w_bridge = 3
            _assert_audit_shape(
                f"{engine_name}/{kd}/bridge", capacity,
                {"bridged_rows": w_bridge},
            )
            inner = eng.make_run(params, n_ticks, donate=False)
            watch_abs = jax.ShapeDtypeStruct((w_bridge,), jnp.int32)
            vk = getattr(abs_state, "view_key", None)
            watched_bytes = (
                n_ticks * w_bridge * capacity
                * (vk.dtype.itemsize if vk is not None else 4)
            )
            bridge_contracts = contracts
            if not contracts.forbid_wide_values:
                bridge_contracts = dataclasses.replace(
                    contracts, no_plane_materialization=False
                )
            programs.append(AuditProgram(
                name=f"{engine_name}/{kd}/bridge",
                engine=engine_name, variant="bridge", key_dtype=kd,
                capacity=capacity, n_ticks=n_ticks,
                fn=jax.jit(
                    lambda state, key, w, _run=inner: _run(
                        state, key, watch_rows=w
                    ),
                    donate_argnums=0,
                ),
                abstract_args=(abs_state, key_abs, watch_abs),
                donated_argnums=(0,),
                contracts=bridge_contracts,
                budget_basis_bytes=state_bytes + watched_bytes,
                wide_threshold=capacity,
            ))

        if kd == dtypes[0] and "telemetry" in want:
            programs.extend(_telemetry_programs(
                eng, params, abs_state, key_abs, capacity, n_ticks, contracts
            ))

        if kd == dtypes[0] and "strategy" in want:
            # r13: every registered non-default (strategy x topology)
            # window enters the matrix under the SAME contracts — the
            # dissemination spec changes the traced program, never the
            # state shape, so the abstract args are shared
            from ..dissemination import DissemSpec

            for strat, topo in contracts.strategy_variants:
                sp = dataclasses.replace(
                    params, dissem=DissemSpec(strategy=strat, topology=topo)
                )
                programs.append(AuditProgram(
                    name=f"{engine_name}/{kd}/strategy-{strat}-{topo}",
                    engine=engine_name, variant="strategy", key_dtype=kd,
                    capacity=capacity, n_ticks=n_ticks,
                    fn=eng.make_run(sp, n_ticks),
                    abstract_args=(abs_state, key_abs),
                    donated_argnums=(0,),
                    contracts=contracts,
                    budget_basis_bytes=state_bytes,
                    wide_threshold=capacity,
                ))

        if kd == dtypes[0] and "adaptive" in want and eng.make_adaptive_run:
            # r14: the adaptive-FD window under the SAME contracts — the
            # AdaptiveState pytree is donated alongside the engine state
            # (argnums 0, 1) and joins the budget basis; the spec changes
            # the traced program, never the engine-state shape
            from ..adaptive import AdaptiveSpec, init_adaptive_state

            ap = dataclasses.replace(
                params, adaptive=AdaptiveSpec(enabled=True)
            )
            abs_ad = _abstract(init_adaptive_state(capacity))
            programs.append(AuditProgram(
                name=f"{engine_name}/{kd}/adaptive",
                engine=engine_name, variant="adaptive", key_dtype=kd,
                capacity=capacity, n_ticks=n_ticks,
                fn=eng.make_adaptive_run(ap, n_ticks),
                abstract_args=(abs_state, abs_ad, key_abs),
                donated_argnums=(0, 1),
                contracts=contracts,
                budget_basis_bytes=state_bytes + _tree_bytes(abs_ad),
                wide_threshold=capacity,
            ))

        if kd == dtypes[0] and "fleet" in want and eng.make_fleet_run:
            # r15: the scenario-batched window — the SAME contracts proved
            # over the vmapped program: every leaf of the stacked [S, ...]
            # state must alias (donation covers the fleet pytree), the
            # program stays transfer-free, no in-scan wide-plane gather
            # feeds only the stacked outputs, pview's wide-value ban holds
            # over the batched values ([S, N, k] carries ONE capacity-
            # scaled dim), and the compiled peak stays within the budget
            # declared PER SCENARIO × S. S stays strictly below capacity
            # so "dim >= capacity" keeps meaning "capacity-scaled".
            s_fleet = DEFAULT_FLEET_SCENARIOS
            _assert_audit_shape(
                f"{engine_name}/{kd}/fleet", capacity,
                {"fleet_scenarios": s_fleet},
            )
            abs_fleet = _fleet_abstracts(abs_state, s_fleet)
            keys_abs = _fleet_abstracts(key_abs, s_fleet)
            fleet_contracts = _fleet_contracts(contracts)
            # audit the SHIPPED fleet program: every production fleet
            # consumer (the MC certification service, config14) runs the
            # quiet_gates=False fleet profile where the engine exposes it
            # — a contract break hiding in the ungated active branches
            # must not slip past a gated audit
            fleet_params = params
            if hasattr(params, "quiet_gates"):
                fleet_params = dataclasses.replace(
                    params, quiet_gates=False
                )
            programs.append(AuditProgram(
                name=f"{engine_name}/{kd}/fleet",
                engine=engine_name, variant="fleet", key_dtype=kd,
                capacity=capacity, n_ticks=n_ticks,
                fn=eng.make_fleet_run(fleet_params, n_ticks),
                abstract_args=(abs_fleet, keys_abs),
                donated_argnums=(0,),
                contracts=fleet_contracts,
                budget_basis_bytes=s_fleet * state_bytes,
                wide_threshold=capacity,
            ))

        if "replay" in want:
            # r18: the incident-replay fleet window — the program
            # ``replay.whatif`` compiles when an incident's scenario
            # carries delay events (SlowEpoch/SlowMember): delay rings
            # armed (delay_slots > 0), quiet gates off, vmapped over the
            # seed axis. The rings add per-link pending planes the plain
            # fleet audit never shapes, so the variant proves the same
            # contracts over the delay-armed IR against a budget basis
            # measured from the delay-armed state.
            s_fleet = DEFAULT_FLEET_SCENARIOS
            replay_params = dataclasses.replace(params, delay_slots=2)
            if hasattr(replay_params, "quiet_gates"):
                replay_params = dataclasses.replace(
                    replay_params, quiet_gates=False
                )
            replay_state = eng.init_state(
                replay_params, n_initial, True, eng.dense_links_default
            )
            abs_replay = _abstract(replay_state)
            replay_bytes = _tree_bytes(abs_replay)
            programs.append(AuditProgram(
                name=f"{engine_name}/{kd}/replay",
                engine=engine_name, variant="replay", key_dtype=kd,
                capacity=capacity, n_ticks=n_ticks,
                fn=eng.make_fleet_run(replay_params, n_ticks),
                abstract_args=(
                    _fleet_abstracts(abs_replay, s_fleet),
                    _fleet_abstracts(_key_abstract(), s_fleet),
                ),
                donated_argnums=(0,),
                contracts=_fleet_contracts(contracts),
                budget_basis_bytes=s_fleet * replay_bytes,
                wide_threshold=capacity,
            ))

        if "fused" in want and eng.make_fused_run:
            # r17: the fused-phase windows — adjacent tick phases share
            # intermediates (pview: packed fd→suspicion/gossip→sweep
            # hand-offs + the delivery combine; sparse: the gossip→sweep
            # coverage hand-off; dense: the shared tail unpack). The fused
            # program is a DIFFERENT jaxpr from the legacy window (that is
            # the point), so it must independently prove the same
            # contracts: full donation aliasing, transfer-freeness, no
            # in-scan wide-plane materialization, pview's wide-value ban
            # over the fused IR, and the engine memory budget.
            programs.append(AuditProgram(
                name=f"{engine_name}/{kd}/fused",
                engine=engine_name, variant="fused", key_dtype=kd,
                capacity=capacity, n_ticks=n_ticks,
                fn=eng.make_fused_run(params, n_ticks),
                abstract_args=(abs_state, key_abs),
                donated_argnums=(0,),
                contracts=contracts,
                budget_basis_bytes=state_bytes,
                wide_threshold=capacity,
            ))

        if (
            kd == dtypes[0] and "fused" in want and engine_name == "pview"
            and eng.make_fused_run
        ):
            # the Pallas-delivery arm of the pview fused window: on CPU the
            # kernel traces in interpret mode (same kernel body as the TPU
            # lowering), and the surrounding program must keep every
            # contract — in particular forbid_wide_values over everything
            # the kernel stages ([N, Wt] payload, [F, N] inverse indices;
            # never two capacity dims)
            pp = dataclasses.replace(params, delivery_kernel="pallas")
            programs.append(AuditProgram(
                name=f"{engine_name}/{kd}/fused-pallas",
                engine=engine_name, variant="fused", key_dtype=kd,
                capacity=capacity, n_ticks=n_ticks,
                fn=eng.make_fused_run(pp, n_ticks),
                abstract_args=(abs_state, key_abs),
                donated_argnums=(0,),
                contracts=contracts,
                budget_basis_bytes=state_bytes,
                wide_threshold=capacity,
            ))

        if (
            kd == dtypes[0] and "fused" in want
            and eng.make_fused_adaptive_run
        ):
            from ..adaptive import AdaptiveSpec, init_adaptive_state

            ap = dataclasses.replace(
                params, adaptive=AdaptiveSpec(enabled=True)
            )
            abs_ad = _abstract(init_adaptive_state(capacity))
            programs.append(AuditProgram(
                name=f"{engine_name}/{kd}/fused-adaptive",
                engine=engine_name, variant="fused", key_dtype=kd,
                capacity=capacity, n_ticks=n_ticks,
                fn=eng.make_fused_adaptive_run(ap, n_ticks),
                abstract_args=(abs_state, abs_ad, key_abs),
                donated_argnums=(0, 1),
                contracts=contracts,
                budget_basis_bytes=state_bytes + _tree_bytes(abs_ad),
                wide_threshold=capacity,
            ))

        if kd == dtypes[0] and "fused" in want and eng.make_fused_fleet_run:
            s_fleet = DEFAULT_FLEET_SCENARIOS
            _assert_audit_shape(
                f"{engine_name}/{kd}/fused-fleet", capacity,
                {"fleet_scenarios": s_fleet},
            )
            fleet_params = params
            if hasattr(params, "quiet_gates"):
                fleet_params = dataclasses.replace(params, quiet_gates=False)
            programs.append(AuditProgram(
                name=f"{engine_name}/{kd}/fused-fleet",
                engine=engine_name, variant="fused", key_dtype=kd,
                capacity=capacity, n_ticks=n_ticks,
                fn=eng.make_fused_fleet_run(fleet_params, n_ticks),
                abstract_args=(
                    _fleet_abstracts(abs_state, s_fleet),
                    _fleet_abstracts(key_abs, s_fleet),
                ),
                donated_argnums=(0,),
                contracts=_fleet_contracts(contracts),
                budget_basis_bytes=s_fleet * state_bytes,
                wide_threshold=capacity,
            ))

        if (
            kd == dtypes[0] and "control" in want and engine_name == "dense"
            and eng.make_fleet_run
        ):
            # r16: the CONTROLLER-EPOCH windows — the exact fleet programs
            # the closed-loop certification harness swaps between as the
            # controller walks its ladder (control.DEFAULT_LADDER: static
            # clean rung + adaptive degraded/storm rungs, each a distinct
            # static params tuple). Every rung's program must satisfy the
            # same contracts as any production fleet window: a controller
            # actuation that lands on an un-audited program would be a
            # hot-swap into unproven territory.
            programs.extend(_control_programs(
                eng, engine_name, kd, capacity, n_ticks, contracts
            ))

        if "sharded" in want and eng.supports_mesh and eng.state_shardings:
            programs.append(_sharded_program(
                eng, engine_name, kd, sharded_capacity, n_ticks, contracts
            ))
            # r20: the sharded twins registered through the descriptor —
            # the FUSED tick over the member mesh and the fleet window on
            # the 2-D scenarios×members mesh ride the same contracts
            # (donation covers the mesh-placed carry, budgets are
            # PER-SHARD) as the base sharded window
            programs.extend(_sharded_r20_programs(
                eng, engine_name, kd, sharded_capacity, n_ticks, contracts,
                mesh2d=kd == dtypes[0],
            ))
            # r21: the mesh observability twins — the sharded telemetry
            # row/append (what arming adds on a mesh driver) and, for
            # pview, one representative sharded phase-split program (the
            # gossip phase, the one that carries the ragged exchange)
            if kd == dtypes[0]:
                programs.extend(_sharded_r21_programs(
                    eng, engine_name, kd, sharded_capacity, n_ticks,
                    contracts,
                ))

    return programs


def _fleet_abstracts(abs_tree, s_fleet: int):
    """[S, ...]-stacked abstract twin of one scenario's abstract pytree —
    the ONE spelling of the fleet batching rule shared by the r15 fleet
    variant and the r16 control variant."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct((s_fleet,) + x.shape, x.dtype),
        abs_tree,
    )


def _fleet_contracts(contracts):
    """Fleet variants prove the memory budget PER SCENARIO × S: swap in
    the engine's declared ``fleet_memory_factor`` when present."""
    if contracts.fleet_memory_factor is None:
        return contracts
    return dataclasses.replace(
        contracts, memory_factor=contracts.fleet_memory_factor
    )


def _control_programs(
    eng, engine_name, kd, capacity, n_ticks, contracts
) -> List[AuditProgram]:
    from ..adaptive import init_adaptive_state
    from ..control import ControlSpec, _fleet_params

    spec = ControlSpec()
    s_fleet = DEFAULT_FLEET_SCENARIOS
    fleet_contracts = _fleet_contracts(contracts)
    keys_abs = _fleet_abstracts(_key_abstract(), s_fleet)
    out: List[AuditProgram] = []
    for rung in spec.ladder:
        rp = _fleet_params(capacity, rung, spec)
        _assert_audit_shape(
            f"{engine_name}/{kd}/control-{rung.name}", capacity,
            {"rumor_slots": rp.rumor_slots, "fleet_scenarios": s_fleet},
        )
        n_initial = max(2, (capacity * 3) // 4)
        state = eng.init_state(rp, n_initial, True, eng.dense_links_default)
        abs_state = _abstract(state)
        abs_fleet = _fleet_abstracts(abs_state, s_fleet)
        basis = s_fleet * _tree_bytes(abs_state)
        if rung.adaptive:
            abs_ad = _fleet_abstracts(
                _abstract(init_adaptive_state(capacity)), s_fleet
            )
            fn = eng.make_fleet_adaptive_run(rp, n_ticks)
            args = (abs_fleet, abs_ad, keys_abs)
            donated = (0, 1)
            basis += _tree_bytes(abs_ad)
        else:
            fn = eng.make_fleet_run(rp, n_ticks)
            args = (abs_fleet, keys_abs)
            donated = (0,)
        out.append(AuditProgram(
            name=f"{engine_name}/{kd}/control-{rung.name}",
            engine=engine_name, variant="control", key_dtype=kd,
            capacity=capacity, n_ticks=n_ticks,
            fn=fn,
            abstract_args=args,
            donated_argnums=donated,
            contracts=fleet_contracts,
            budget_basis_bytes=basis,
            wide_threshold=capacity,
        ))
    return out


def _telemetry_programs(
    eng, params, abs_state, key_abs, capacity, n_ticks, contracts
) -> List[AuditProgram]:
    """The r8 armed path's device programs: the per-window ring-row
    reduction (engine ``telemetry_window_vector`` + sentinel columns, the
    exact ``TelemetryPlane._row_fn`` spelling) and the donated ring append
    (the exact ``MetricRing._append`` spelling). The armed WINDOW program
    is the unarmed one — arming changes what happens to the window's
    outputs, not the window (the r8 neutrality proof); these two programs
    are what arming adds."""
    from ..telemetry.plane import SENTINEL_SERIES

    # abstract per-window metrics: shape-evaluate the undonated window
    undonated = eng.make_run(params, n_ticks, donate=False)
    out_abs = jax.eval_shape(lambda s, k: undonated(s, k), abs_state, key_abs)
    ms_abs = out_abs[2]

    vector_fn = eng.telemetry_window_vector

    def _row(ms, state, false_dead, key_regr):
        return jnp.concatenate([
            vector_fn(ms, state),
            jnp.stack([false_dead, key_regr]).astype(jnp.float32),
        ])

    scalar = jax.ShapeDtypeStruct((), jnp.int32)
    row_fn = jax.jit(_row)
    n_series = len(eng.telemetry_series) + len(SENTINEL_SERIES)
    ring_len = 64
    _assert_audit_shape(
        f"{eng.name}/telemetry", capacity,
        {"ring_len": ring_len, "n_series": n_series},
    )
    ring_abs = jax.ShapeDtypeStruct((ring_len, n_series), jnp.float32)
    row_abs = jax.ShapeDtypeStruct((n_series,), jnp.float32)

    append_fn = jax.jit(lambda buf, row, idx: buf.at[idx].set(row),
                        donate_argnums=0)

    return [
        AuditProgram(
            name=f"{eng.name}/i32/telemetry-row",
            engine=eng.name, variant="telemetry-row", key_dtype="i32",
            capacity=capacity, n_ticks=n_ticks,
            fn=row_fn,
            abstract_args=(ms_abs, abs_state, scalar, scalar),
            donated_argnums=(),
            contracts=contracts,
            budget_basis_bytes=_tree_bytes(abs_state) + _tree_bytes(ms_abs),
            wide_threshold=capacity,
            is_window=False,
        ),
        AuditProgram(
            name=f"{eng.name}/i32/telemetry-append",
            engine=eng.name, variant="telemetry-append", key_dtype="i32",
            capacity=capacity, n_ticks=n_ticks,
            fn=append_fn,
            abstract_args=(ring_abs, row_abs, scalar),
            donated_argnums=(0,),
            contracts=contracts,
            budget_basis_bytes=_tree_bytes(ring_abs),
            wide_threshold=capacity,
            is_window=False,
        ),
    ]


def _sharded_program(
    eng, engine_name, kd, capacity, n_ticks, contracts
) -> AuditProgram:
    """The mesh-sharded window over every local device, lowered on
    abstract row-sharded inputs (no state materialized on the mesh)."""
    from ..ops.sharding import make_mesh

    mesh = make_mesh()
    params = _audit_params(engine_name, capacity, kd)
    n_initial = max(2, (capacity * 3) // 4)
    dense_links = eng.dense_links_default
    state = eng.init_state(params, n_initial, True, dense_links)
    shardings = eng.state_shardings(mesh, dense_links, params.delay_slots)
    abs_state = _abstract(state, shardings)
    fn = eng.make_sharded_run(mesh, params, n_ticks, dense_links)
    return AuditProgram(
        name=f"{engine_name}/{kd}/sharded",
        engine=engine_name, variant="sharded", key_dtype=kd,
        capacity=capacity, n_ticks=n_ticks,
        fn=fn,
        abstract_args=(abs_state, _key_abstract()),
        donated_argnums=(0,),
        contracts=contracts,
        budget_basis_bytes=_tree_bytes(abs_state, per_device=True),
        wide_threshold=capacity,
        mesh_size=mesh.size,
    )


def _sharded_r20_programs(
    eng, engine_name, kd, capacity, n_ticks, contracts, mesh2d: bool = True
) -> List[AuditProgram]:
    """The r20 sharded twins: ``{engine}/{kd}/sharded-fused`` (the FUSED
    tick over the member mesh — same ragged delivery exchange, same
    donated carry) and ``{engine}/{kd}/sharded-mesh2d`` (the r15 fleet
    axis composed with the member axis on a 2-D scenarios×members mesh).
    Both lower on abstract mesh-placed inputs; the memory budget basis is
    PER SHARD (the 2-D program's basis is one scenario-row's shard set ×
    S scenarios, matching the fleet per-scenario × S convention)."""
    from ..ops.sharding import make_mesh

    out: List[AuditProgram] = []
    if eng.make_sharded_fused_run is None and eng.make_sharded_fleet_run is None:
        return out
    mesh = make_mesh()
    params = _audit_params(engine_name, capacity, kd)
    n_initial = max(2, (capacity * 3) // 4)
    dense_links = eng.dense_links_default
    state = eng.init_state(params, n_initial, True, dense_links)
    shardings = eng.state_shardings(mesh, dense_links, params.delay_slots)

    if eng.make_sharded_fused_run is not None:
        abs_state = _abstract(state, shardings)
        out.append(AuditProgram(
            name=f"{engine_name}/{kd}/sharded-fused",
            engine=engine_name, variant="sharded", key_dtype=kd,
            capacity=capacity, n_ticks=n_ticks,
            fn=eng.make_sharded_fused_run(mesh, params, n_ticks),
            abstract_args=(abs_state, _key_abstract()),
            donated_argnums=(0,),
            contracts=contracts,
            budget_basis_bytes=_tree_bytes(abs_state, per_device=True),
            wide_threshold=capacity,
            mesh_size=mesh.size,
        ))

    if (mesh2d and eng.make_sharded_fleet_run is not None
            and len(mesh.devices.ravel()) >= 2):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..ops.fleet import FLEET_AXIS
        from ..ops.sharding import make_pview_mesh2d

        devices = list(mesh.devices.ravel())
        s_sc = 2
        mesh2d = make_pview_mesh2d(s_sc, devices)
        shard2d = eng.state_shardings(mesh2d, dense_links, params.delay_slots)

        def lift(x, sh):
            spec = P() if not x.size else P(FLEET_AXIS, *sh.spec)
            return jax.ShapeDtypeStruct(
                (s_sc,) + x.shape, x.dtype,
                sharding=NamedSharding(mesh2d, spec),
            )

        abs_fleet = jax.tree.map(lift, state, shard2d)
        k = jax.random.PRNGKey(0)
        keys_abs = jax.ShapeDtypeStruct(
            (s_sc,) + k.shape, k.dtype,
            sharding=NamedSharding(mesh2d, P(FLEET_AXIS, None)),
        )
        _assert_audit_shape(
            f"{engine_name}/{kd}/sharded-mesh2d", capacity,
            {"fleet_scenarios": s_sc},
        )
        out.append(AuditProgram(
            name=f"{engine_name}/{kd}/sharded-mesh2d",
            engine=engine_name, variant="sharded", key_dtype=kd,
            capacity=capacity, n_ticks=n_ticks,
            fn=eng.make_sharded_fleet_run(mesh2d, params, n_ticks),
            abstract_args=(abs_fleet, keys_abs),
            donated_argnums=(0,),
            contracts=_fleet_contracts(contracts),
            budget_basis_bytes=_tree_bytes(abs_fleet, per_device=True),
            wide_threshold=capacity,
            mesh_size=mesh2d.size,
        ))
    return out


def _sharded_r21_programs(
    eng, engine_name, kd, capacity, n_ticks, contracts
) -> List[AuditProgram]:
    """The r21 mesh-observability twins: ``sharded-telemetry-row`` (the
    exact ``TelemetryPlane._row_fn`` spelling on a mesh driver — the row
    reduction over the SHARDED window's metric outputs, pinned replicated
    on the way out) and ``sharded-telemetry-append`` (the descriptor's
    ``make_sharded_telemetry_append``, the donated replicated ring write).
    For pview one sharded phase-split program rides along
    (``sharded-profile-gossip``): the gossip phase traced under the ragged
    delivery context, the program the mesh profiler times."""
    from ..ops.sharding import make_mesh, make_sharded_telemetry_row
    from ..telemetry.plane import SENTINEL_SERIES

    mesh = make_mesh()
    params = _audit_params(engine_name, capacity, kd)
    n_initial = max(2, (capacity * 3) // 4)
    dense_links = eng.dense_links_default
    state = eng.init_state(params, n_initial, True, dense_links)
    shardings = eng.state_shardings(mesh, dense_links, params.delay_slots)
    abs_state = _abstract(state, shardings)
    key_abs = _key_abstract()

    # abstract per-window metrics from the SHARDED window's own output
    # signature — on pview this carries the mesh-only ``delivery_overflow``
    # column the unsharded window never emits
    sharded = eng.make_sharded_run(mesh, params, n_ticks, dense_links)
    out_abs = jax.eval_shape(lambda s, k: sharded(s, k), abs_state, key_abs)
    ms_abs = out_abs[2]

    vector_fn = eng.telemetry_window_vector

    def _row(ms, st, false_dead, key_regr):
        return jnp.concatenate([
            vector_fn(ms, st),
            jnp.stack([false_dead, key_regr]).astype(jnp.float32),
        ])

    scalar = jax.ShapeDtypeStruct((), jnp.int32)
    n_series = len(eng.telemetry_series) + len(SENTINEL_SERIES)
    ring_len = 64
    ring_abs = jax.ShapeDtypeStruct((ring_len, n_series), jnp.float32)
    row_abs = jax.ShapeDtypeStruct((n_series,), jnp.float32)

    out = [
        AuditProgram(
            name=f"{engine_name}/{kd}/sharded-telemetry-row",
            engine=engine_name, variant="sharded", key_dtype=kd,
            capacity=capacity, n_ticks=n_ticks,
            fn=make_sharded_telemetry_row(mesh, _row),
            abstract_args=(ms_abs, abs_state, scalar, scalar),
            donated_argnums=(),
            contracts=contracts,
            budget_basis_bytes=(
                _tree_bytes(abs_state, per_device=True) + _tree_bytes(ms_abs)
            ),
            wide_threshold=capacity,
            is_window=False,
            mesh_size=mesh.size,
        ),
        AuditProgram(
            name=f"{engine_name}/{kd}/sharded-telemetry-append",
            engine=engine_name, variant="sharded", key_dtype=kd,
            capacity=capacity, n_ticks=n_ticks,
            fn=eng.make_sharded_telemetry_append(mesh),
            abstract_args=(ring_abs, row_abs, scalar),
            donated_argnums=(0,),
            contracts=contracts,
            budget_basis_bytes=_tree_bytes(ring_abs),
            wide_threshold=capacity,
            is_window=False,
            mesh_size=mesh.size,
        ),
    ]

    if engine_name == "pview":
        from ..ops.rand import draw_sparse_round
        from ..trace.profile import _pview_phase_fns

        gossip = _pview_phase_fns(params, mesh=mesh)["gossip"]
        r_abs = jax.eval_shape(
            lambda k: draw_sparse_round(
                k, params.capacity, params.fanout, params.sample_tries
            ),
            key_abs,
        )
        out.append(AuditProgram(
            name=f"{engine_name}/{kd}/sharded-profile-gossip",
            engine=engine_name, variant="sharded", key_dtype=kd,
            capacity=capacity, n_ticks=n_ticks,
            fn=gossip,
            abstract_args=(abs_state, r_abs),
            donated_argnums=(),
            contracts=contracts,
            budget_basis_bytes=_tree_bytes(abs_state, per_device=True),
            wide_threshold=capacity,
            is_window=False,
            mesh_size=mesh.size,
        ))
    return out


def build_matrix(
    engines: Optional[Sequence[str]] = None,
    capacity: int = DEFAULT_CAPACITY,
    n_ticks: int = DEFAULT_N_TICKS,
    variants: Optional[Sequence[str]] = None,
    sharded_capacity: int = DEFAULT_SHARDED_CAPACITY,
) -> List[AuditProgram]:
    """The full engine × key-dtype × variant audit matrix."""
    out: List[AuditProgram] = []
    for name in engines or ("dense", "sparse", "pview"):
        out.extend(build_engine_programs(
            name, capacity=capacity, n_ticks=n_ticks, variants=variants,
            sharded_capacity=sharded_capacity,
        ))
    return out
