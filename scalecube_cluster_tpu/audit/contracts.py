"""The contract checkers: prove each r6–r11 invariant over the program IR.

Each checker takes one :class:`.programs.AuditProgram` and returns a list
of :class:`Violation` — empty means PROVED over the program the compiler
actually builds (not sampled from a run, not matched from source text):

* :func:`check_donation_alias` — r6: every donated leaf is aliased into
  the lowered program (``tf.aliasing_output`` / ``donated_invars``), and
  no donated input escapes the program unchanged alongside its aliased
  update (the use-after-free shape: the caller would read freed memory
  through the returned alias).
* :func:`check_transfer_free` — r6/r8/r10: no host-callback or
  infeed/outfeed primitive anywhere in the closed jaxpr. This is the
  IR-level superset of ``tools/lint_host_callbacks.py`` — a callback
  reached through decorator indirection or a re-exported helper never
  appears as a matchable attribute chain in source, but it is always a
  ``*_callback`` equation in the jaxpr.
* :func:`check_no_plane_materialization` — r10 (the measured ~18%/tick
  lesson): no gather/dynamic-slice of a capacity²-wide plane inside the
  window scan whose value escapes ONLY to the per-tick stacked outputs.
  Such a consumer forces XLA to materialize an extra full-plane copy per
  tick; window-boundary diffs (the r10 design) are free.
* :func:`check_forbid_wide_values` — r11, pview only: NO value anywhere
  in the closed jaxpr has two or more capacity-scaled dims. The source
  lint (plane-dtype rule 3) bans *allocations*; this bans every
  intermediate the compiler builds, which is the actual O(N·k) claim.
* :func:`check_memory_budget` — r9/r11: the compiled program's
  ``memory_analysis`` peak stays within the engine's declared budget
  (``factor ×`` one state copy ``+ overhead``) — the max-N ladders'
  feasibility rule as a per-engine regression gate.

:func:`run_contracts` dispatches the applicable subset for one program;
:func:`check_restore_seams` closes the loop on the r6 restore rule by
running the AST donation lint over each engine's registered
``restore_module`` (zero-copy host aliases enter donatable state through
``restore()``, which no jaxpr can see — the lint is the right tool, the
registry makes it per-engine).
"""

from __future__ import annotations

import dataclasses
import importlib
import re
from typing import Callable, Dict, List, Optional

from . import jaxpr_walk as W
from .programs import MIB, AuditProgram

#: jaxpr primitives that reach the host from inside a program (the lint's
#: attribute chains, at the IR level where indirection cannot hide them)
TRANSFER_PRIMITIVES = {
    "pure_callback": "pure_callback bakes a host round trip into the program",
    "io_callback": "io_callback bakes a host round trip into the program",
    "debug_callback": "debug callback (jax.debug.print/callback) runs on host "
                      "per traced invocation",
    "outside_call": "host_callback outside_call is a device->host escape",
    "infeed": "infeed synchronizes with a host feeder thread",
    "outfeed": "outfeed pushes device values to a host listener",
}


@dataclasses.dataclass(frozen=True)
class Violation:
    """One broken contract, with an actionable pointer."""

    contract: str  # "donation_alias" | "transfer_free" | ...
    program: str  # AuditProgram.name
    message: str
    where: str = ""  # source provenance (file:line (fn)) when known

    def __str__(self) -> str:
        loc = f" [{self.where}]" if self.where else ""
        return f"{self.program}: {self.contract}: {self.message}{loc}"


# -- 1. donation-alias verifier ----------------------------------------------

_ARG_SPLIT_RE = re.compile(r"%arg(\d+):")


def _mlir_donated_args(mlir_text: str) -> Dict[int, str]:
    """arg position -> donation annotation, from the lowered module's entry
    signature. A donated parameter carries ``tf.aliasing_output = K``
    (single-device: jax already knows which output reuses the buffer) or
    ``jax.buffer_donor = true`` (sharded: XLA's buffer assignment picks the
    aliasing, and the COMPILED module's ``input_output_alias`` shows it —
    see :func:`_compiled_aliased_params`). Parsed per argument fragment so
    an unannotated arg can never swallow its neighbor's annotation."""
    out: Dict[int, str] = {}
    parts = _ARG_SPLIT_RE.split(mlir_text)
    # parts = [prefix, argnum, fragment, argnum, fragment, ...]; each
    # fragment runs to the NEXT %arg token, so arg attrs stay with their arg
    for argnum, fragment in zip(parts[1::2], parts[2::2]):
        # attrs sit in the leading {...} block before the signature's arrow
        head = fragment.split("->", 1)[0]
        m = re.search(r"tf\.aliasing_output\s*=\s*(\d+)", head)
        if m:
            out[int(argnum)] = f"tf.aliasing_output = {m.group(1)}"
        elif re.search(r"jax\.buffer_donor\s*=\s*true", head):
            out[int(argnum)] = "jax.buffer_donor"
    return out


def _compiled_aliased_params(hlo_text: str) -> Optional[set]:
    """Parameter numbers in the compiled module's ``input_output_alias``
    header — what XLA's buffer assignment ACCEPTED; None when the module
    declares no alias map at all."""
    key = "input_output_alias={"
    i = hlo_text.find(key)
    if i < 0:
        return None
    j = i + len(key)
    depth = 1
    while depth and j < len(hlo_text):
        ch = hlo_text[j]
        depth += ch == "{"
        depth -= ch == "}"
        j += 1
    body = hlo_text[i + len(key):j]
    return {int(m.group(1)) for m in re.finditer(r"\}:\s*\((\d+),", body)}


def _kept_flat_positions(prog: AuditProgram) -> List[int]:
    """Flat invar positions the program actually USES, in order — jit's
    lowering DROPS unused arguments (``kept_var_idx``) and numbers MLIR
    ``%argN`` / compiled parameters over the kept ones only, so flat leaf
    positions must be remapped through this list before comparing against
    either. Usedness is judged on the traced pjit's INNER jaxpr (the outer
    wrapper trivially passes every arg through)."""
    jaxpr = prog.closed_jaxpr.jaxpr
    inner = jaxpr
    if len(jaxpr.eqns) == 1 and jaxpr.eqns[0].primitive.name == "pjit":
        sub = jaxpr.eqns[0].params.get("jaxpr")
        if sub is not None and len(sub.jaxpr.invars) == len(jaxpr.invars):
            inner = sub.jaxpr
    used = set()
    for eqn in inner.eqns:
        for iv in eqn.invars:
            if W.is_var(iv):
                used.add(id(iv))
    for ov in inner.outvars:
        if W.is_var(ov):
            used.add(id(ov))
    return [i for i, iv in enumerate(inner.invars) if id(iv) in used]


def check_donation_alias(
    prog: AuditProgram, use_compiled: bool = False
) -> List[Violation]:
    donated = prog.donated_leaf_info()
    if not donated:
        return []  # program donates nothing; no claim to verify
    violations: List[Violation] = []

    # flat position -> lowered/compiled argument number (unused args are
    # dropped by lowering, shifting every later argument's number)
    kept = _kept_flat_positions(prog)
    arg_number = {flat: i for i, flat in enumerate(kept)}

    # (a) every donated leaf must be USED and annotated in the LOWERED
    # program — an unused donated leaf means the builder no longer threads
    # that buffer at all (its donation is vacuous and the state it holds is
    # dead weight), and an unannotated one means the donation was dropped
    annotated = _mlir_donated_args(prog.mlir_text)
    for pos, path, nbytes in donated:
        if nbytes == 0:
            continue
        if pos not in arg_number:
            violations.append(Violation(
                "donation_alias", prog.name,
                f"donated leaf {path} (flat arg {pos}, {nbytes} B) is "
                "UNUSED by the program — lowering drops the argument, the "
                "donation is vacuous, and the buffer never updates in "
                "place (r6 discipline requires every donated leaf to be "
                "threaded through the window)",
            ))
        elif arg_number[pos] not in annotated:
            violations.append(Violation(
                "donation_alias", prog.name,
                f"donated leaf {path} (flat arg {pos}, {nbytes} B) carries "
                "neither tf.aliasing_output nor jax.buffer_donor in the "
                "lowered program — the donation was dropped and the window "
                "silently degrades to a copying dispatch (r6)",
            ))

    # (a') when compiled, the optimized module's input_output_alias map is
    # the ground truth: XLA's buffer assignment must have ACCEPTED an alias
    # for every donated leaf (a may-alias hint XLA declined — e.g. a donor
    # whose buffer is still live at output time — shows up only here)
    if use_compiled and not violations:
        accepted = _compiled_aliased_params(prog.compiled().as_text())
        if accepted is None:
            violations.append(Violation(
                "donation_alias", prog.name,
                "compiled module declares NO input_output_alias map despite "
                f"{len(donated)} donated leaves — the whole donation was "
                "dropped at compile time (r6 copying dispatch)",
            ))
        else:
            for pos, path, nbytes in donated:
                if nbytes > 0 and arg_number.get(pos) not in accepted:
                    violations.append(Violation(
                        "donation_alias", prog.name,
                        f"donated leaf {path} (param {arg_number.get(pos)}, "
                        f"{nbytes} B) is absent from the compiled "
                        "input_output_alias map — XLA declined the alias, "
                        "so this window copies the buffer every dispatch "
                        "(r6)",
                    ))

    # (b) no donated input may escape unchanged alongside its aliased
    # update — the caller would hold a stale reference into freed memory
    closed = prog.closed_jaxpr
    invars = closed.jaxpr.invars
    donated_positions = {pos for pos, _, _ in donated}
    path_by_pos = {pos: path for pos, path, _ in donated}
    outvar_ids = {id(v) for v in closed.jaxpr.outvars if W.is_var(v)}
    for pos, iv in enumerate(invars):
        if pos in donated_positions and id(iv) in outvar_ids:
            violations.append(Violation(
                "donation_alias", prog.name,
                f"donated leaf {path_by_pos[pos]} (flat arg {pos}) escapes "
                "the program UNCHANGED alongside its aliased update — the "
                "r6 use-after-free shape (the returned value aliases a "
                "buffer the donation frees); return only the updated array",
            ))
    return violations


# -- 2. transfer-freeness prover ---------------------------------------------


def check_transfer_free(prog: AuditProgram) -> List[Violation]:
    violations: List[Violation] = []
    for eqn, _ in W.walk_eqns(prog.closed_jaxpr.jaxpr):
        why = TRANSFER_PRIMITIVES.get(eqn.primitive.name)
        if why is not None:
            violations.append(Violation(
                "transfer_free", prog.name,
                f"primitive '{eqn.primitive.name}' in the closed jaxpr: "
                f"{why} — the r6 zero-per-window-transfer discipline bans "
                "it from every window program",
                where=W.provenance(eqn),
            ))
    return violations


# -- 3. in-scan wide-plane materialization detector ---------------------------


def check_no_plane_materialization(prog: AuditProgram) -> List[Violation]:
    if not prog.is_window:
        return []
    violations: List[Violation] = []
    for scan_eqn in W.outer_scans(prog.closed_jaxpr.jaxpr):
        if scan_eqn.params.get("length") != prog.n_ticks:
            continue  # not the window loop
        body = scan_eqn.params["jaxpr"].jaxpr
        nc = scan_eqn.params["num_carry"]
        carry_out = [v for v in body.outvars[:nc] if W.is_var(v)]
        ys_out = [v for v in body.outvars[nc:] if W.is_var(v)]
        producer: Dict[int, int] = {}
        for i, eqn in enumerate(body.eqns):
            for ov in eqn.outvars:
                if W.is_var(ov):
                    producer[id(ov)] = i

        def reach(roots) -> set:
            seen_eqns: set = set()
            seen_vars: set = set()
            stack = list(roots)
            while stack:
                v = stack.pop()
                if id(v) in seen_vars:
                    continue
                seen_vars.add(id(v))
                i = producer.get(id(v))
                if i is None or i in seen_eqns:
                    continue
                seen_eqns.add(i)
                for iv in body.eqns[i].invars:
                    if W.is_var(iv):
                        stack.append(iv)
            return seen_eqns

        feeds_carry = reach(carry_out)
        feeds_ys = reach(ys_out)
        for i, eqn in enumerate(body.eqns):
            if i in feeds_ys and i not in feeds_carry:
                hit = W.find_wide_gather(eqn, prog.wide_threshold)
                if hit is not None:
                    op = next(
                        (v for v in hit.invars if W.is_var(v)), None
                    )
                    shape = tuple(op.aval.shape) if op is not None else "?"
                    violations.append(Violation(
                        "no_plane_materialization", prog.name,
                        f"in-scan {hit.primitive.name} of wide plane "
                        f"{shape} feeds ONLY the per-tick stacked outputs "
                        "— this forces an extra full-plane materialization "
                        "every tick (the measured r10 ~18% pattern); "
                        "capture it as a window-boundary diff instead",
                        where=W.provenance(hit),
                    ))
    return violations


# -- 4. pview O(N·k) wide-value ban ------------------------------------------


def check_forbid_wide_values(prog: AuditProgram) -> List[Violation]:
    if not prog.contracts.forbid_wide_values:
        return []
    violations: List[Violation] = []
    seen_shapes: set = set()
    # program inputs and closure CONSTANTS first (a wide lookup table baked
    # in as a closed-over const never appears as an eqn output), then every
    # equation output at any depth
    jaxpr = prog.closed_jaxpr.jaxpr
    for v, kind in [(iv, "INPUT") for iv in jaxpr.invars] + [
        (cv, "CLOSURE CONSTANT") for cv in jaxpr.constvars
    ]:
        if W.is_var(v) and W.is_wide(v.aval, prog.wide_threshold):
            shape = tuple(v.aval.shape)
            if shape not in seen_shapes:
                seen_shapes.add(shape)
                violations.append(Violation(
                    "forbid_wide_values", prog.name,
                    f"program {kind} of capacity-squared shape {shape} — "
                    "the partial-view engine admits no [N, N]-proportional "
                    "value anywhere (O(N·k) contract, r11)",
                ))
    for eqn, _ in W.walk_eqns(prog.closed_jaxpr.jaxpr):
        candidates = [(ov, f"built by '{eqn.primitive.name}'")
                      for ov in eqn.outvars]
        for sj in W.sub_jaxprs(eqn):
            candidates.extend(
                (cv, f"closed over by a '{eqn.primitive.name}' sub-jaxpr")
                for cv in sj.constvars
            )
        for ov, how in candidates:
            if W.is_var(ov) and W.is_wide(ov.aval, prog.wide_threshold):
                shape = tuple(ov.aval.shape)
                if shape in seen_shapes:
                    continue
                seen_shapes.add(shape)
                violations.append(Violation(
                    "forbid_wide_values", prog.name,
                    f"intermediate value of capacity-squared shape {shape} "
                    f"{how} — the O(N·k) guarantee must hold for every "
                    "value the compiler builds, not just stored state "
                    "(r11)",
                    where=W.provenance(eqn),
                ))
    return violations


# -- 5. memory-budget gate ----------------------------------------------------


def check_memory_budget(prog: AuditProgram) -> List[Violation]:
    mem = prog.memory()
    peak = mem["peak_live_bytes"]
    budget = int(
        prog.contracts.memory_factor * prog.budget_basis_bytes
        + prog.contracts.memory_overhead_mib * MIB
    )
    if peak > budget:
        return [Violation(
            "memory_budget", prog.name,
            f"compiled peak {peak} B ({peak / MIB:.2f} MiB) exceeds the "
            f"declared budget {budget} B = {prog.contracts.memory_factor} × "
            f"state {prog.budget_basis_bytes} B + "
            f"{prog.contracts.memory_overhead_mib} MiB overhead "
            f"(memory_analysis: args {mem.get('argument_size_in_bytes')}, "
            f"out {mem.get('output_size_in_bytes')}, "
            f"temps {mem.get('temp_size_in_bytes')}, "
            f"aliased -{mem.get('alias_size_in_bytes')})",
        )]
    return []


# -- 6. restore-seam check (AST lint through the contract registry) ----------


def check_restore_seams(
    engine_names=None, modules: Optional[Dict[str, str]] = None
) -> List[Violation]:
    """Run the donation-safety AST lint over every engine's registered
    ``restore_module`` — the one contract a jaxpr cannot witness (the
    zero-copy alias happens on the HOST, before any program runs).

    ``modules`` overrides the registry with an explicit
    ``{name: module-or-file-path}`` map (the falsifiability tests seed a
    known-bad restore module through it)."""
    import os
    import sys

    tools_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    if tools_root not in sys.path:  # tools/ is repo-root-anchored
        sys.path.insert(0, tools_root)
    from tools.lint_donation_safety import lint_file

    if modules is None:
        from ..ops import engine_api

        modules = {}
        for name in engine_names or ("dense", "sparse", "pview"):
            modules[name] = engine_api.engine(name).contracts.restore_module

    violations: List[Violation] = []
    for name, module in modules.items():
        if not module:
            violations.append(Violation(
                "restore_seam", name,
                "engine registers no restore_module — the r6 copy=True "
                "restore rule is unverifiable for it; set "
                "EngineContracts.restore_module",
            ))
            continue
        path = (
            module if os.path.exists(module)
            else importlib.import_module(module).__file__
        )
        for f in lint_file(path):
            violations.append(Violation(
                "restore_seam", name,
                f"{f.message} (in {f.function})",
                where=f"{f.path}:{f.line}",
            ))
    return violations


# -- dispatch -----------------------------------------------------------------

#: checker registry: contract name -> (enabled-for, callable)
CHECKERS: Dict[str, Callable[[AuditProgram], List[Violation]]] = {
    "donation_alias": check_donation_alias,
    "transfer_free": check_transfer_free,
    "no_plane_materialization": check_no_plane_materialization,
    "forbid_wide_values": check_forbid_wide_values,
    "memory_budget": check_memory_budget,
}


def applicable_contracts(prog: AuditProgram, compile_programs: bool = True):
    c = prog.contracts
    names = []
    if c.donation_alias:
        names.append("donation_alias")
    if c.transfer_free:
        names.append("transfer_free")
    if c.no_plane_materialization and prog.is_window:
        names.append("no_plane_materialization")
    if c.forbid_wide_values:
        names.append("forbid_wide_values")
    if compile_programs:
        names.append("memory_budget")
    return names


def run_contracts(
    prog: AuditProgram, compile_programs: bool = True
) -> Dict[str, List[Violation]]:
    """Every applicable contract for one program. ``compile_programs=False``
    skips the AOT compile (memory budget + optimized-HLO alias facts) and
    audits the traced/lowered forms only — the fast tier-1 mode."""
    out: Dict[str, List[Violation]] = {}
    for name in applicable_contracts(prog, compile_programs):
        if name == "donation_alias":
            out[name] = check_donation_alias(
                prog, use_compiled=compile_programs
            )
        else:
            out[name] = CHECKERS[name](prog)
    return out
