"""On-device SWIM invariant sentinels for chaos scenarios.

Four protocol guarantees, each checkable as a pure reduction over the
device-resident view planes (``view_key`` / ``up`` — shared by the dense and
sparse states, so ONE reduction serves both engines, mesh-sharded included):

1. **No false-DEAD** — a member no scenario event ever faulted must never be
   marked DEAD by any up observer (the fault-tolerant rumor-spreading
   guarantee: adversarial loss below the storm-immunity threshold must not
   kill healthy members).
2. **Bounded detection latency** — after ``Crash(rows, at)``, every up
   observer marks each crashed row DEAD (or never knew it) within the
   detection budget (suspicion math + dissemination slack).
3. **Re-convergence** — after a heal/restart/storm-end boundary, all up
   members see all up members ALIVE within the convergence budget (the
   anti-entropy guarantee; seed-row SYNC is what re-bridges full splits).
4. **Key/incarnation monotonicity** — each member's self record (packed
   ``epoch | incarnation | rank`` key) never regresses between checks: the
   lattice's monotone-merge contract, which all other guarantees build on.

Every sentinel fact is LATCHING or monotone (a DEAD tombstone persists until
rejoin, detection and convergence only ever become true, a key regression is
counted against a remembered previous value), so the checks are sound under
SAMPLING: the runner evaluates them every ``check_interval`` ticks — pure
jnp ops staged on device through the r6 deferred-readback discipline, ZERO
device→host transfers until a sync point (``health_snapshot`` / ``GET
/chaos`` / the final report) reads the accumulators back.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from .events import (
    ChurnStorm,
    Crash,
    DroppedRefute,
    LinkFlap,
    LossStorm,
    Partition,
    Restart,
    Scenario,
    SlowEpoch,
    ZoneOutage,
)


def _ceil_log2(n: int) -> int:
    return int(n).bit_length() if n > 0 else 0


def default_detect_budget(params) -> int:
    """Suspicion math + dissemination slack, in ticks: the suspicion window
    (``suspicion_mult * ceilLog2(N) * fd_every``) doubled (first-probe and
    expiry-sweep phase lag), plus two SYNC intervals for the DEAD record to
    reach every observer through anti-entropy even if gossip misses some."""
    return (
        2 * params.suspicion_mult * _ceil_log2(params.capacity) * params.fd_every
        + 2 * params.sync_every
    )


def default_converge_budget(params) -> int:
    """Post-heal re-convergence is anti-entropy-limited for the stragglers
    (nodes that must learn of their own premature death via a periodic
    seed-SYNC and refute — benchmarks/config4_partition.py budgets 8 sync
    intervals for the same reason), plus the detection slack for any death
    rumors still in flight at the heal. The raw budget is scaled by the
    armed dissemination strategy/topology (r13,
    :func:`dissemination_budget_scale`)."""
    raw = 8 * params.sync_every + default_detect_budget(params)
    return max(1, int(round(raw * dissemination_budget_scale(params))))


#: r13 strategy-aware re-convergence scaling. Deterministic schedules
#: (pipelined/accelerated) TIGHTEN the budget: their chord rotation
#: guarantees every overlay edge is exercised within one rotation, so the
#: gossip-driven share of re-convergence loses its coupon-collector tail.
#: Constrained topologies LOOSEN it by their diameter class (ring linear,
#: torus 2-D), and a WAN-delayed geo overlay loosens further with the
#: configured cross-zone delay (every inter-zone anti-entropy exchange
#: pays the delay both ways).
_STRATEGY_SCALE = {
    "push": 1.0, "push_pull": 1.0, "pipelined": 0.75, "accelerated": 0.75,
    # tuneable (r14): the deterministic share covers the rotation, the
    # random share keeps coupon-collector tails — neither tighten nor loosen
    "tuneable": 1.0,
}
_TOPOLOGY_SCALE = {
    "full": 1.0, "expander": 1.0, "ring": 1.5, "torus": 1.25, "geo": 2.0,
}


def scenario_budget_scale(scenario: Scenario) -> tuple:
    """(detect_scale, converge_scale) the r18 fault vocabulary applies on
    top of the protocol-math defaults — scenario-content-driven slack,
    multiplicative with the r13 dissemination scaling:

    * ``SlowEpoch`` inflates every gossip/anti-entropy hop by the scripted
      mean delay, so both budgets stretch with it (capped — a sentinel
      budget is generous by design, not a bound proof);
    * ``ChurnStorm`` leaves one wave's death rumors still in flight at the
      next wave's restart, so re-convergence stretches with the wave count;
    * ``DroppedRefute`` forces the squashed rows to out-gossip a fully
      disseminated suspicion (or DEAD tombstone) after the window ends.

    Explicit ``Scenario.detect_budget`` / ``converge_budget`` are never
    scaled — a scripted budget wins verbatim.
    """
    d_scale = c_scale = 1.0
    for ev in scenario.events:
        if isinstance(ev, SlowEpoch):
            s = min(3.0, 1.0 + ev.mean_delay_ticks / 8.0)
            d_scale = max(d_scale, s)
            c_scale = max(c_scale, s)
        elif isinstance(ev, ChurnStorm):
            c_scale = max(c_scale, 1.0 + 0.25 * (ev.waves - 1))
        elif isinstance(ev, DroppedRefute):
            c_scale = max(c_scale, 1.5)
    return d_scale, c_scale


def dissemination_budget_scale(params) -> float:
    """Multiplier the auto re-convergence budget applies for the armed
    dissemination spec (1.0 for the default push/full and for params
    objects that predate the spec)."""
    spec = getattr(params, "dissem", None)
    if spec is None or spec.is_default:
        return 1.0
    scale = _STRATEGY_SCALE[spec.strategy] * _TOPOLOGY_SCALE[spec.topology]
    if spec.topology == "geo" and spec.geo_wan_delay_ticks:
        scale *= 1.0 + spec.geo_wan_delay_ticks / 64.0
    return scale


@dataclass
class SentinelSpec:
    """Host-side compiled sentinel plan for one scenario (numpy arrays are
    uploaded once at arm time; the per-check work is all on device)."""

    capacity: int
    never_faulted: np.ndarray  # bool [N]
    crash_rows: np.ndarray  # i32 [K] — one entry per crashed row occurrence
    crash_at: np.ndarray  # i32 [K]
    crash_deadline: np.ndarray  # i32 [K]
    crash_until: np.ndarray  # i32 [K] — restart tick (or horizon)
    conv_from: np.ndarray  # i32 [C] — heal/restart/storm-end boundaries
    conv_deadline: np.ndarray  # i32 [C]
    conv_labels: List[str] = field(default_factory=list)
    detect_budget: int = 0
    converge_budget: int = 0
    check_interval: int = 32
    horizon: int = 0
    #: r14 false-positive watch cohort: degraded-but-alive rows (SlowMember/
    #: AsymmetricLoss/FlakyObserver targets + Scenario.fp_watch_rows). A
    #: watched row tombstoned by any up observer is a false positive;
    #: fp_enforce=False records without judging (the static control arm).
    fp_watch: np.ndarray = field(default_factory=lambda: np.zeros(0, bool))
    fp_enforce: bool = True

    def device_arrays(self, t0: int = 0) -> Dict[str, object]:
        """Upload the spec once at arm time. ``t0`` is the absolute tick the
        scenario was armed at; sentinel checks compare ``state.tick - t0``
        against the (relative) event ticks, so detect/conv stamps come back
        in scenario-relative ticks like every deadline in the report.
        The ``fp_watch`` plane ships only when the cohort is non-empty —
        legacy scenarios keep their exact legacy check program."""
        import jax.numpy as jnp

        out = {
            "t0": jnp.int32(t0),
            "never_faulted": jnp.asarray(self.never_faulted),
            "crash_rows": jnp.asarray(self.crash_rows),
            "crash_at": jnp.asarray(self.crash_at),
            "crash_until": jnp.asarray(self.crash_until),
            "conv_from": jnp.asarray(self.conv_from),
        }
        if self.fp_watch.size and bool(self.fp_watch.any()):
            out["fp_watch"] = jnp.asarray(self.fp_watch)
        return out


def build_spec(
    scenario: Scenario, params, config=None, horizon: Optional[int] = None
) -> SentinelSpec:
    """Compile a scenario + engine params (``SimParams`` / ``SparseParams`` —
    only the shared protocol knobs are read) into a :class:`SentinelSpec`.
    ``config`` (a ClusterConfig) supplies ``chaos.*`` defaults; explicit
    scenario fields win."""
    n = params.capacity
    scenario.validate_rows(n)
    chaos_cfg = getattr(config, "chaos", None)
    immunity = getattr(chaos_cfg, "loss_storm_immunity_pct", 50.0)
    detect = scenario.detect_budget or getattr(
        chaos_cfg, "detect_budget_ticks", 0
    ) or default_detect_budget(params)
    converge = scenario.converge_budget or getattr(
        chaos_cfg, "converge_budget_ticks", 0
    ) or default_converge_budget(params)
    d_scale, c_scale = scenario_budget_scale(scenario)
    if not scenario.detect_budget:
        detect = max(1, int(round(detect * d_scale)))
    if not scenario.converge_budget:
        converge = max(1, int(round(converge * c_scale)))
    check = scenario.check_interval or getattr(
        chaos_cfg, "check_interval_ticks", 0
    ) or 32
    # sampling must be able to observe a detection/convergence before its
    # deadline passes; clamp the cadence well inside the tightest budget
    check = max(1, min(check, detect // 4 or 1, converge // 4 or 1))

    touched = scenario.fault_touched_rows(n, immunity)
    never = np.ones((n,), bool)
    never[sorted(touched)] = False

    # r14 false-positive cohort: degraded-but-alive rows plus explicit
    # fp_watch_rows (explicit rows are NOT crash-excluded — that is the
    # falsifiability hook: watch a row you then crash and the sentinel
    # must fire)
    fp = np.zeros((n,), bool)
    fp_rows = sorted(
        set(scenario.degraded_rows()) | set(scenario.fp_watch_rows)
    )
    fp[[r for r in fp_rows if 0 <= r < n]] = True

    crash_rows: List[int] = []
    crash_at: List[int] = []
    crash_until: List[int] = []
    conv_from: List[int] = []
    conv_labels: List[str] = []
    restarts: List[Restart] = [e for e in scenario.events if isinstance(e, Restart)]
    for ev in scenario.events:
        if isinstance(ev, Crash):
            for r in ev.rows:
                until = min(
                    (rs.at for rs in restarts if r in rs.rows and rs.at > ev.at),
                    default=np.iinfo(np.int32).max,
                )
                crash_rows.append(r)
                crash_at.append(ev.at)
                crash_until.append(until)
        elif isinstance(ev, Partition) and ev.heal_at is not None:
            conv_from.append(ev.heal_at)
            conv_labels.append(f"partition_heal@{ev.heal_at}")
        elif isinstance(ev, Restart):
            conv_from.append(ev.at)
            conv_labels.append(f"restart@{ev.at}")
        elif isinstance(ev, LossStorm) and ev.until is not None:
            conv_from.append(ev.until)
            conv_labels.append(f"storm_end@{ev.until}")
        elif isinstance(ev, LinkFlap) and ev.until is not None:
            conv_from.append(ev.until)
            conv_labels.append(f"flap_end@{ev.until}")
        elif isinstance(ev, ZoneOutage) and ev.until is not None:
            conv_from.append(ev.until)
            conv_labels.append(f"zone_up@{ev.until}")
        elif isinstance(ev, ChurnStorm):
            # each wave is a crash obligation (lapsing at its own restart,
            # like a Crash/Restart pair) and each restart a convergence point
            for w, (c_tick, r_tick, chunk) in enumerate(ev.wave_schedule()):
                for r in chunk:
                    crash_rows.append(r)
                    crash_at.append(c_tick)
                    crash_until.append(r_tick)
                conv_from.append(r_tick)
                conv_labels.append(f"churn_restart[w{w}]@{r_tick}")
        elif isinstance(ev, SlowEpoch):
            conv_from.append(ev.until)
            conv_labels.append(f"slow_epoch_end@{ev.until}")
        elif isinstance(ev, DroppedRefute):
            # after the drop window the rows must out-refute whatever
            # verdict accumulated and the cluster must re-converge
            conv_from.append(ev.until)
            conv_labels.append(f"refute_resume@{ev.until}")

    spec = SentinelSpec(
        capacity=n,
        never_faulted=never,
        crash_rows=np.asarray(crash_rows, np.int32),
        crash_at=np.asarray(crash_at, np.int32),
        crash_deadline=np.asarray([a + detect for a in crash_at], np.int32),
        crash_until=np.asarray(crash_until, np.int32),
        conv_from=np.asarray(conv_from, np.int32),
        conv_deadline=np.asarray([f + converge for f in conv_from], np.int32),
        conv_labels=conv_labels,
        detect_budget=detect,
        converge_budget=converge,
        check_interval=check,
        fp_watch=fp,
        fp_enforce=scenario.fp_enforce,
    )
    auto_horizon = max(
        scenario.last_event_tick() + 1,
        int(max(spec.crash_deadline, default=0)),
        int(max(spec.conv_deadline, default=0)),
        2 * check,
    )
    spec.horizon = horizon or scenario.horizon or auto_horizon
    return spec


def init_sentinel_state(
    view_key, spec: SentinelSpec, sparse: bool = False
) -> Dict[str, object]:
    """Fresh device-side sentinel accumulators, baselined on the current
    view (one diag gather — a device op, not a transfer). ``sparse`` adds
    the sparse engine's internal-consistency counter (``n_live`` drift)."""
    import jax.numpy as jnp

    n = spec.capacity
    rows = jnp.arange(n)
    sent = {
        "prev_diag": view_key[rows, rows],
        "key_regressions": jnp.int32(0),
        "false_dead_max": jnp.int32(0),
        "detect_tick": jnp.full((len(spec.crash_rows),), -1, jnp.int32),
        "conv_tick": jnp.full((len(spec.conv_from),), -1, jnp.int32),
    }
    if spec.fp_watch.size and bool(spec.fp_watch.any()):
        sent["fp_dead_max"] = jnp.int32(0)
    if sparse:
        sent["n_live_drift"] = jnp.int32(0)
    return sent


def sentinel_report(sent_host: Dict[str, np.ndarray], spec: SentinelSpec,
                    final_tick: int) -> dict:
    """Fold the read-back accumulators into the structured scenario report
    (the one host-side step; everything before it stayed on device)."""
    detections = []
    for k in range(len(spec.crash_rows)):
        det = int(sent_host["detect_tick"][k])
        deadline = int(spec.crash_deadline[k])
        # only judge deadlines the run actually reached, and only crashes
        # that PERSISTED through their whole budget — a quick-blip crash
        # restarted before the deadline lapses the obligation (detection
        # inside a window shorter than the suspicion math is impossible,
        # and the restart's own convergence point takes over)
        judged = (
            final_tick >= deadline and int(spec.crash_until[k]) >= deadline
        )
        ok = (det >= 0 and det <= deadline) or not judged
        detections.append({
            "row": int(spec.crash_rows[k]),
            "crashed_at": int(spec.crash_at[k]),
            "deadline": deadline,
            "detected_at": det if det >= 0 else None,
            "ok": bool(ok),
        })
    convergence = []
    for c in range(len(spec.conv_from)):
        conv = int(sent_host["conv_tick"][c])
        deadline = int(spec.conv_deadline[c])
        judged = final_tick >= deadline
        ok = (conv >= 0 and conv <= deadline) or not judged
        convergence.append({
            "label": spec.conv_labels[c],
            "from": int(spec.conv_from[c]),
            "deadline": deadline,
            "converged_at": conv if conv >= 0 else None,
            "ok": bool(ok),
        })
    false_dead = int(sent_host["false_dead_max"])
    regress = int(sent_host["key_regressions"])
    n_live_drift = int(sent_host.get("n_live_drift", 0))
    # pview's internal-consistency sentinel (duplicate/self table entries —
    # the partial-view analogue of the sparse n_live drift)
    view_breaks = int(sent_host.get("view_invariant_breaks", 0))
    # r14 false-positive sentinel: degraded-but-alive members tombstoned.
    # Judged only when the scenario enforces it — the static-timeout
    # control arm RECORDS its false positives without failing the run.
    fp_dead = int(sent_host.get("fp_dead_max", 0))
    fp_judged = "fp_dead_max" in sent_host and spec.fp_enforce
    violations = (
        (1 if false_dead else 0)
        + (1 if regress else 0)
        + (1 if n_live_drift else 0)
        + (1 if view_breaks else 0)
        + (1 if (fp_judged and fp_dead) else 0)
        + sum(1 for d in detections if not d["ok"])
        + sum(1 for c in convergence if not c["ok"])
    )
    report = {
        "false_dead_members_max": false_dead,
        "key_regressions": regress,
        "detections": detections,
        "convergence": convergence,
        "never_faulted_members": int(spec.never_faulted.sum()),
        "detect_budget": spec.detect_budget,
        "converge_budget": spec.converge_budget,
        "check_interval": spec.check_interval,
        "violations": violations,
        "ok": violations == 0,
    }
    if "fp_dead_max" in sent_host:
        report["false_positive_dead_max"] = fp_dead
        report["false_positive_enforced"] = bool(spec.fp_enforce)
        report["false_positive_watch_members"] = int(spec.fp_watch.sum())
    if "n_live_drift" in sent_host:
        report["n_live_drift"] = n_live_drift
    if "view_invariant_breaks" in sent_host:
        report["view_invariant_breaks"] = view_breaks
    return report
