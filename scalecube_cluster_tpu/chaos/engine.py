"""Scenario compilation + runners for every engine.

One scenario, four code paths:

* :class:`StateTimeline` compiles a :class:`.events.Scenario` into ordered
  between-window mutations of a device-resident state (dense ``SimState`` or
  sparse ``SparseState`` — the two ops modules expose the same mutator
  names, and mesh-sharded states go through the identical functions).
* :class:`DriverChaosRunner` / :func:`run_driver_scenario` drive a
  ``SimDriver`` through a scenario with the on-device sentinels armed —
  zero per-window device→host transfers (the r6 discipline); the final
  report (or a ``/chaos`` poll) is the one sync point.
* :class:`EmulatorChaosRunner` replays the same schedule onto
  :class:`..transport.emulator.NetworkEmulator` instances for the
  scalar/real-transport engine (crash = total network isolation, the
  reference testlib idiom for killing a node without stopping its process).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .events import (
    AsymmetricLoss,
    ChurnStorm,
    Crash,
    DroppedRefute,
    FlakyObserver,
    LinkFlap,
    LossStorm,
    Partition,
    Restart,
    Scenario,
    ScenarioError,
    SlowEpoch,
    SlowMember,
    ZoneOutage,
)
from .sentinels import build_spec, sentinel_report


def _state_capacity(st) -> int:
    """Member capacity N of a serial OR fleet-stacked state: the up mask's
    LAST axis. ``st.capacity`` reads ``up.shape[0]``, which is the SCENARIO
    count S on an [S, N]-stacked fleet state — closures that enumerate
    "everyone" from it would silently touch only the first S rows (or, when
    S > N, mask the bug entirely behind clamped scatter writes)."""
    return st.up.shape[-1]


@dataclass(frozen=True)
class _Step:
    """One scheduled timeline action (engine-agnostic)."""

    tick: int
    seq: int
    kind: str
    label: str
    payload: tuple


def _window(ev, end_attr: str):
    end = getattr(ev, end_attr, None)
    return ev.at, (float("inf") if end is None else end)


def _validate_degraded_composition(scenario: Scenario) -> None:
    """The r14 degraded family's start/end handlers WRITE the loss/delay
    planes they touch; compositions whose teardown would clobber another
    active event's links are refused LOUDLY here (both runners route
    through :func:`schedule`) instead of silently mis-modelling:

    * two ``SlowMember`` events overlapping in time — each covers every
      link touching its cohort, so the earlier ``until`` zeroes delay on
      the cross-cohort links the later event still owns;
    * overlapping ``AsymmetricLoss``/``FlakyObserver`` events with
      intersecting cohorts — the shared links' loss is last-writer-wins;
    * a degraded event overlapping an active ``Partition`` or ``LinkFlap``
      window — the degraded writes would overwrite (and its teardown
      lift) the block plane on shared links. ``LossStorm`` composes on the
      device engines (the storm stash replays loss mutations) and is
      checked separately by the emulator runner, whose single
      default-settings slot cannot stash.
    """
    from .events import DEGRADED_EVENT_TYPES

    deg = [e for e in scenario.events if isinstance(e, DEGRADED_EVENT_TYPES)]
    for i in range(len(deg)):
        a0, a1 = _window(deg[i], "until")
        for j in range(i + 1, len(deg)):
            b0, b1 = _window(deg[j], "until")
            if not (a0 < b1 and b0 < a1):
                continue
            both_slow = isinstance(deg[i], SlowMember) and isinstance(
                deg[j], SlowMember
            )
            if both_slow or (set(deg[i].rows) & set(deg[j].rows)):
                raise ScenarioError(
                    f"{type(deg[i]).__name__}{list(deg[i].rows)} and "
                    f"{type(deg[j]).__name__}{list(deg[j].rows)} overlap in "
                    "time on shared links — the earlier teardown would "
                    "clobber the later event's plane; stagger the windows"
                )
    blocks = [
        (ev, _window(ev, "heal_at")) for ev in scenario.events
        if isinstance(ev, Partition)
    ] + [
        (ev, _window(ev, "until")) for ev in scenario.events
        if isinstance(ev, (LinkFlap, ZoneOutage))
    ]
    for d in deg:
        d0, d1 = _window(d, "until")
        for bev, (b0, b1) in blocks:
            if d0 < b1 and b0 < d1:
                raise ScenarioError(
                    f"{type(d).__name__}{list(d.rows)} overlaps an active "
                    f"{type(bev).__name__}: the degraded family's loss/delay "
                    "writes would overwrite (and its teardown lift) the "
                    "block plane on shared links — stagger the events"
                )
    # r18 SlowEpoch writes the WHOLE delay plane; any overlapping SlowMember
    # (or second SlowEpoch) shares links with it and the earlier teardown
    # zeroes delay the later event still owns — same refusal as above
    slows = [e for e in scenario.events
             if isinstance(e, (SlowEpoch, SlowMember))]
    for i in range(len(slows)):
        if not isinstance(slows[i], SlowEpoch):
            continue
        a0, a1 = _window(slows[i], "until")
        for j in range(len(slows)):
            if j == i:
                continue
            b0, b1 = _window(slows[j], "until")
            if a0 < b1 and b0 < a1:
                raise ScenarioError(
                    f"SlowEpoch@{slows[i].at} overlaps "
                    f"{type(slows[j]).__name__}@{slows[j].at} in time — both "
                    "write the delay plane and the earlier teardown would "
                    "zero the later event's links; stagger the windows"
                )


def _restart_actions(scenario: Scenario):
    """Every (tick, rows) restart action, whether from a ``Restart`` event
    or a ``ChurnStorm`` wave — shared by composition checks and budgets."""
    out = []
    for ev in scenario.events:
        if isinstance(ev, Restart):
            out.append((ev.at, ev.rows))
        elif isinstance(ev, ChurnStorm):
            for _, r_tick, chunk in ev.wave_schedule():
                out.append((r_tick, chunk))
    return out


def _validate_refute_composition(scenario: Scenario) -> None:
    """A restart inside an active ``DroppedRefute`` window on the same row
    would have its fresh-identity epoch bump squashed back by the drop (the
    drop cannot tell a refute's inc bump from a restart's epoch bump) —
    refuse the composition loudly instead of silently un-restarting."""
    drops = [e for e in scenario.events if isinstance(e, DroppedRefute)]
    if not drops:
        return
    for t, rows in _restart_actions(scenario):
        for d in drops:
            hit = set(rows) & set(d.rows)
            if hit and d.at <= t < d.until:
                raise ScenarioError(
                    f"restart of rows {sorted(hit)} at tick {t} lands inside "
                    f"DroppedRefute{list(d.rows)}@[{d.at},{d.until}) — the "
                    "drop would squash the fresh identity's epoch bump; "
                    "restart after the drop window ends"
                )


def schedule(scenario: Scenario, horizon: Optional[int] = None) -> List[_Step]:
    """Expand a scenario into the ordered (tick, seq) action list both the
    state and the emulator runners replay. Flap toggles materialize here;
    a flap always ends CLEAR (a trailing up-toggle at ``until``). Degraded
    events (r14) that would compose silently-wrong with block events are
    refused at compile time (:func:`_validate_degraded_composition`)."""
    _validate_degraded_composition(scenario)
    _validate_refute_composition(scenario)
    steps: List[_Step] = []
    seq = itertools.count()
    for ev in scenario.events:
        if isinstance(ev, Partition):
            steps.append(_Step(ev.at, next(seq), "partition_block",
                               f"partition@{ev.at}", (ev.groups,)))
            if ev.heal_at is not None:
                steps.append(_Step(ev.heal_at, next(seq), "partition_heal",
                                   f"heal@{ev.heal_at}", (ev.groups,)))
        elif isinstance(ev, LossStorm):
            steps.append(_Step(ev.at, next(seq), "storm_start",
                               f"storm({ev.pct}%)@{ev.at}", (ev.pct,)))
            if ev.until is not None:
                steps.append(_Step(ev.until, next(seq), "storm_end",
                                   f"storm_end@{ev.until}", ()))
        elif isinstance(ev, LinkFlap):
            until = ev.until if ev.until is not None else horizon
            if until is None:
                raise ScenarioError(
                    "LinkFlap without `until` needs a scenario horizon"
                )
            for k, t in enumerate(range(ev.at, until, ev.period)):
                kind = "flap_down" if k % 2 == 0 else "flap_up"
                steps.append(_Step(t, next(seq), kind, f"{kind}@{t}", (ev.pairs,)))
            steps.append(_Step(until, next(seq), "flap_up",
                               f"flap_end@{until}", (ev.pairs,)))
        elif isinstance(ev, SlowMember):
            steps.append(_Step(ev.at, next(seq), "slow_start",
                               f"slow({ev.mean_delay_ticks}t){list(ev.rows)}@{ev.at}",
                               (ev.rows, ev.mean_delay_ticks)))
            if ev.until is not None:
                steps.append(_Step(ev.until, next(seq), "slow_end",
                                   f"slow_end@{ev.until}", (ev.rows,)))
        elif isinstance(ev, (AsymmetricLoss, FlakyObserver)):
            direction = getattr(ev, "direction", "out")
            steps.append(_Step(ev.at, next(seq), "asym_start",
                               f"asym({ev.pct}%/{direction}){list(ev.rows)}@{ev.at}",
                               (ev.rows, ev.pct, direction)))
            if ev.until is not None:
                steps.append(_Step(ev.until, next(seq), "asym_end",
                                   f"asym_end@{ev.until}", (ev.rows, direction)))
        elif isinstance(ev, Crash):
            steps.append(_Step(ev.at, next(seq), "crash",
                               f"crash{list(ev.rows)}@{ev.at}", (ev.rows,)))
        elif isinstance(ev, Restart):
            steps.append(_Step(ev.at, next(seq), "restart",
                               f"restart{list(ev.rows)}@{ev.at}",
                               (ev.rows, ev.seed_rows)))
        elif isinstance(ev, ZoneOutage):
            steps.append(_Step(ev.at, next(seq), "zone_down",
                               f"zone_down{list(ev.rows)}@{ev.at}", (ev.rows,)))
            if ev.until is not None:
                steps.append(_Step(ev.until, next(seq), "zone_up",
                                   f"zone_up{list(ev.rows)}@{ev.until}",
                                   (ev.rows,)))
        elif isinstance(ev, ChurnStorm):
            # a churn storm compiles PURELY into the existing crash/restart
            # vocabulary — every runner (device timeline, driver identity
            # bookkeeping, emulator isolation) handles it with zero new kinds
            for w, (c_tick, r_tick, chunk) in enumerate(ev.wave_schedule()):
                steps.append(_Step(c_tick, next(seq), "crash",
                                   f"churn_crash[w{w}]{list(chunk)}@{c_tick}",
                                   (chunk,)))
                steps.append(_Step(r_tick, next(seq), "restart",
                                   f"churn_restart[w{w}]{list(chunk)}@{r_tick}",
                                   (chunk, ev.seed_rows)))
        elif isinstance(ev, SlowEpoch):
            steps.append(_Step(ev.at, next(seq), "slow_epoch_start",
                               f"slow_epoch({ev.mean_delay_ticks}t)@{ev.at}",
                               (ev.mean_delay_ticks,)))
            steps.append(_Step(ev.until, next(seq), "slow_epoch_end",
                               f"slow_epoch_end@{ev.until}", ()))
        elif isinstance(ev, DroppedRefute):
            # per-tick expansion (the LinkFlap precedent): a refute bumped
            # during tick t cannot spread before t+1 (the refute phase runs
            # AFTER gossip/sync inside a tick), so squashing at every
            # between-window seam in [at, until) suppresses every refute
            # before it disseminates
            for t in range(ev.at, ev.until):
                steps.append(_Step(t, next(seq), "refute_drop",
                                   f"refute_drop{list(ev.rows)}@{t}",
                                   (ev.rows,)))
    steps.sort(key=lambda s: (s.tick, s.seq))
    return steps


# ---------------------------------------------------------------------------
# device-state timeline (dense / sparse / sharded)
# ---------------------------------------------------------------------------


class StateTimeline:
    """Replays the schedule onto a device-resident state via the engine's ops
    module (``ops.state`` or ``ops.sparse`` — same mutator surface).

    Loss-storm semantics on dense links: the pre-storm loss matrix is
    stashed (an independent device copy — the live plane gets donated away
    by the next window) and the storm applies a FLOOR (existing blocks stay
    blocked). Link mutations made while the storm is active are recorded
    and replayed on top of the restored matrix at storm end, so a partition
    that started mid-storm survives it and one healed mid-storm stays
    healed.

    ``on_restart(state, row, seed_rows) -> state`` lets a driver hook its
    member-identity bookkeeping into Restart events; default is the raw
    ``ops.join_row``.
    """

    def __init__(
        self,
        scenario: Scenario,
        ops,
        dense_links: bool,
        on_restart: Optional[Callable] = None,
        horizon: Optional[int] = None,
    ):
        self._ops = ops
        self._on_restart = on_restart
        self._steps = schedule(scenario, horizon=horizon)
        self._i = 0
        self._storm_stash = None  # pre-storm loss plane (independent copy)
        self._storm_pct = 0.0  # active storm's floor, as a probability
        self._storm_replay: List[Callable] = []
        # group-partition-capable engines (pview's part_id/part_loss model)
        # run Partition events without an [N, N] link plane; per-PAIR flaps
        # still need one
        group_parts = getattr(ops, "GROUP_PARTITIONS", False)
        engine_label = {
            "state": "dense", "sparse": "sparse", "pview": "pview",
        }.get(getattr(ops, "__name__", "?").rsplit(".", 1)[-1],
              getattr(ops, "__name__", "?"))
        for s in self._steps:
            if s.kind == "refute_drop" and not hasattr(ops, "drop_refutes"):
                # name the offending event AND the engine: a multi-event
                # production dump that trips this (e.g. during whatif) must
                # point at the one step that can't run here, not issue a
                # bare capability error (ISSUE 18 satellite)
                raise ScenarioError(
                    f"event {s.label!r} (DroppedRefute) needs the dense "
                    "[N, N] view/changed_at planes (ops.drop_refutes), "
                    f"which the {engine_label!r} engine does not expose — "
                    "run the scenario on the dense engine"
                )
        if not dense_links:
            for s in self._steps:
                if s.kind in ("partition_block", "partition_heal",
                              "zone_down", "zone_up") and not group_parts:
                    raise ScenarioError(
                        f"{s.kind} needs per-link (dense) links; this engine "
                        "runs scalar uniform loss — construct the driver "
                        "with dense_links=True"
                    )
                if s.kind in ("flap_down", "flap_up"):
                    raise ScenarioError(
                        f"{s.kind} needs per-link (dense) links; this engine "
                        "has no per-pair link plane"
                    )
                if s.kind in ("slow_start", "slow_end", "asym_start",
                              "asym_end", "slow_epoch_start",
                              "slow_epoch_end"):
                    raise ScenarioError(
                        f"{s.kind} (loss-adversarial family) needs "
                        "per-link (dense) links; this engine has no "
                        "per-pair link plane — run these scenarios on the "
                        "dense engine (dense_links=True)"
                    )

    def next_tick(self) -> Optional[int]:
        return self._steps[self._i].tick if self._i < len(self._steps) else None

    def boundaries(self) -> List[int]:
        return sorted({s.tick for s in self._steps})

    def apply_due(self, state, tick: int):
        """Apply every action scheduled at or before ``tick``; returns
        (state, labels). Pure device ops — nothing is read back."""
        labels: List[str] = []
        while self._i < len(self._steps) and self._steps[self._i].tick <= tick:
            step = self._steps[self._i]
            self._i += 1
            state = self._apply(state, step)
            labels.append(step.label)
        return state, labels

    # -- one action ----------------------------------------------------------
    def _apply(self, state, step: _Step):
        ops = self._ops
        if step.kind == "partition_block":
            (groups,) = step.payload

            def fn(st, groups=groups, clear=0.0):
                for a, b in itertools.combinations(groups, 2):
                    st = ops.block_partition(st, list(a), list(b))
                return st

        elif step.kind == "partition_heal":
            (groups,) = step.payload

            def fn(st, groups=groups, clear=0.0):
                for a, b in itertools.combinations(groups, 2):
                    st = self._heal_pair(st, list(a), list(b), clear)
                return st

        elif step.kind == "flap_down":
            (pairs,) = step.payload

            def fn(st, pairs=pairs, clear=0.0):
                for s, d in pairs:
                    st = ops.set_link_loss(st, [s], [d], 1.0)
                return st

        elif step.kind == "flap_up":
            (pairs,) = step.payload

            def fn(st, pairs=pairs, clear=0.0):
                for s, d in pairs:
                    st = ops.set_link_loss(st, [s], [d], clear)
                return st

        elif step.kind == "slow_start":
            rows, delay = step.payload

            def fn(st, rows=rows, delay=delay):
                # exponential-mean delay on every link touching the cohort
                # (both directions) — ops.set_link_delay validates that the
                # engine's delay rings are armed (params.delay_slots > 0)
                n = _state_capacity(st)
                everyone = list(range(n))
                st = ops.set_link_delay(st, everyone, list(rows), float(delay))
                return ops.set_link_delay(st, list(rows), everyone, float(delay))

        elif step.kind == "slow_end":
            (rows,) = step.payload

            def fn(st, rows=rows):
                n = _state_capacity(st)
                everyone = list(range(n))
                st = ops.set_link_delay(st, everyone, list(rows), 0.0)
                return ops.set_link_delay(st, list(rows), everyone, 0.0)

        elif step.kind == "asym_start":
            rows, pct, direction = step.payload

            def fn(st, rows=rows, p=pct / 100.0, d=direction, clear=None):
                # ``clear`` is the storm-replay convention's floor: an asym
                # write landing DURING a LossStorm must not punch a
                # below-floor hole in the uniform storm (the LossStorm
                # contract) — apply max(pct, floor); the clean variant
                # replays on the restored matrix at storm end
                eff = p if clear is None else max(p, clear)
                n = _state_capacity(st)
                everyone = list(range(n))
                if d in ("in", "both"):
                    st = ops.set_link_loss(st, everyone, list(rows), eff)
                if d in ("out", "both"):
                    st = ops.set_link_loss(st, list(rows), everyone, eff)
                return st

        elif step.kind == "asym_end":
            rows, direction = step.payload

            def fn(st, rows=rows, d=direction, clear=0.0):
                n = _state_capacity(st)
                everyone = list(range(n))
                if d in ("in", "both"):
                    st = ops.set_link_loss(st, everyone, list(rows), clear)
                if d in ("out", "both"):
                    st = ops.set_link_loss(st, list(rows), everyone, clear)
                return st

        elif step.kind == "crash":
            (rows,) = step.payload

            def fn(st, rows=rows):
                return ops.crash_rows(st, list(rows))

        elif step.kind == "restart":
            rows, seed_rows = step.payload

            def fn(st, rows=rows, seed_rows=seed_rows):
                for r in rows:
                    if self._on_restart is not None:
                        st = self._on_restart(st, r, list(seed_rows))
                    else:
                        st = ops.join_row(st, r, list(seed_rows))
                return st

        elif step.kind == "zone_down":
            (rows,) = step.payload

            def fn(st, rows=rows, clear=0.0):
                rest = [r for r in range(_state_capacity(st)) if r not in set(rows)]
                if not rest:
                    return st
                return ops.block_partition(st, list(rows), rest)

        elif step.kind == "zone_up":
            (rows,) = step.payload

            def fn(st, rows=rows, clear=0.0):
                rest = [r for r in range(_state_capacity(st)) if r not in set(rows)]
                if not rest:
                    return st
                return self._heal_pair(st, list(rows), rest, clear)

        elif step.kind == "slow_epoch_start":
            (delay,) = step.payload

            def fn(st, delay=delay):
                everyone = list(range(_state_capacity(st)))
                return ops.set_link_delay(st, everyone, everyone, float(delay))

        elif step.kind == "slow_epoch_end":

            def fn(st):
                everyone = list(range(_state_capacity(st)))
                return ops.set_link_delay(st, everyone, everyone, 0.0)

        elif step.kind == "refute_drop":
            (rows,) = step.payload

            def fn(st, rows=rows):
                return ops.drop_refutes(st, list(rows))

        elif step.kind == "storm_start":
            (pct,) = step.payload
            return self._storm_start(state, pct)
        elif step.kind == "storm_end":
            return self._storm_end(state)
        else:  # pragma: no cover - schedule() only emits the kinds above
            raise ScenarioError(f"unknown timeline action {step.kind!r}")

        if self._storm_stash is not None and step.kind in (
            "partition_block", "partition_heal", "flap_down", "flap_up",
            "asym_start", "asym_end", "zone_down", "zone_up",
        ):
            # the CLEAN variant replays on the restored matrix at storm end;
            # during the storm, links that clear only drop to the storm
            # FLOOR (a mid-storm heal must not punch a loss-0 hole in the
            # uniform storm the LossStorm contract promises)
            self._storm_replay.append(fn)
            return fn(state, clear=self._storm_pct)
        return fn(state)

    def _heal_pair(self, st, a, b, clear):
        """Heal the directed block between row groups ``a`` and ``b``. Routes
        through ``ops.heal_partition_pair`` when the ops module names the
        operation (the fleet layer intercepts it to vary per-scenario
        partition assignments); the fallback is the value-identical legacy
        spelling, two directed ``set_link_loss`` writes."""
        heal = getattr(self._ops, "heal_partition_pair", None)
        if heal is not None:
            return heal(st, list(a), list(b), clear)
        st = self._ops.set_link_loss(st, list(a), list(b), clear)
        return self._ops.set_link_loss(st, list(b), list(a), clear)

    def _storm_start(self, state, pct: float):
        import jax.numpy as jnp

        if self._storm_stash is not None:
            raise ScenarioError("overlapping LossStorms are not supported")
        # independent copy: the live plane is donated away next window
        self._storm_stash = jnp.array(state.loss, copy=True)
        self._storm_pct = pct / 100.0
        self._storm_replay = []
        return self._ops.set_uniform_loss(state, pct / 100.0, floor=True)

    def _storm_end(self, state):
        if self._storm_stash is None:
            raise ScenarioError("storm_end without an active storm")
        loss = self._storm_stash
        self._storm_stash = None
        if loss.ndim == 0:
            # pass the device scalar through (a float() here would be a
            # device→host transfer mid-scenario)
            state = self._ops.set_uniform_loss(state, loss)
        else:
            from ..ops.state import _roundtrip

            state = state.replace(loss=loss, fetch_rt=_roundtrip(loss))
        for fn in self._storm_replay:
            state = fn(state)
        self._storm_replay = []
        return state


# ---------------------------------------------------------------------------
# SimDriver runner (dense / sparse / mesh-sharded)
# ---------------------------------------------------------------------------


class DriverChaosRunner:
    """One scenario armed on one :class:`..sim.SimDriver`.

    Arming registers the runner on the driver (``driver._chaos``) so
    ``health_snapshot()`` and the monitor's ``GET /chaos`` can report live
    sentinel state; :meth:`run` drives the scenario to its horizon. The
    stepping loop performs NO device→host transfers: fault injection and
    sentinel checks are pure device ops, and the one readback happens in the
    final report (or whenever a monitor poll explicitly asks)."""

    def __init__(self, driver, scenario: Scenario, config=None,
                 sentinels: bool = True, trace: bool = False):
        import jax

        self.driver = driver
        self.scenario = scenario
        self._untraced_crash_rows: List[int] = []
        if trace:
            crash_rows = []
            for ev in scenario.events:
                if isinstance(ev, Crash):
                    crash_rows.extend(int(r) for r in ev.rows)
                elif isinstance(ev, ChurnStorm):
                    crash_rows.extend(int(r) for r in ev.rows)
            uniq = tuple(dict.fromkeys(crash_rows))
            if driver._trace is None:
                # auto-attach (r10): the scenario's crashed rows are the
                # members whose causal story the report will need —
                # sample them as tracers (up to the configured
                # TraceConfig.tracers budget) so sentinel outcomes
                # resolve to span trees. On a mesh driver this raises
                # (arm_trace's own rule) — an explicit trace=True must
                # not silently degrade to an untraced report.
                from ..config import ClusterConfig, TraceConfig

                tcfg = config if isinstance(
                    config, (ClusterConfig, TraceConfig)
                ) else None
                trace_cfg = tcfg.trace if isinstance(tcfg, ClusterConfig) \
                    else (tcfg or TraceConfig())
                driver.arm_trace(
                    config=tcfg, tracer_rows=uniq[:trace_cfg.tracers] or None
                )
            # no silent caps: crashed rows the (auto- OR pre-) armed spec
            # does not trace are named in the report — a missing span
            # tree must read as "untraced", never "no detection activity"
            self._untraced_crash_rows = [
                r for r in uniq if r not in driver._trace.spec.tracer_rows
            ]
        with driver._lock:
            self.t0 = int(driver.state.tick)  # the one arm-time readback
            arm_state = driver.state
        self.spec = build_spec(scenario, driver.params, config=config)
        self.timeline = StateTimeline(
            scenario,
            driver._ops,
            dense_links=driver._dense_links,
            on_restart=self._restart,
            horizon=self.spec.horizon,
        )
        # sentinel init + reduce through the engine interface (r11): dense/
        # sparse run the shared view-plane core, pview its table-edge twin
        from ..ops import engine_api

        eng = engine_api.of_driver(driver)
        self._sent = eng.sentinel_init(arm_state, self.spec) if sentinels else None
        self._spec_dev = self.spec.device_arrays(self.t0)
        self._check = jax.jit(eng.sentinel_reduce)
        self.events_applied: List[Tuple[int, str]] = []
        self.rel_tick = 0
        self.max_window = 32
        self.done = False
        self.last_report: Optional[dict] = None
        driver._chaos = self
        # armed telemetry (r8): scenario lifecycle + applied fault events
        # flow onto the unified event bus, and a violated final report
        # triggers a flight-recorder dump (see _publish / run)
        self._publish("scenario_armed", scenario=scenario.name,
                      horizon=self.spec.horizon)

    def _publish(self, kind: str, **fields) -> None:
        plane = getattr(self.driver, "_telemetry", None)
        if plane is not None:
            plane.bus.publish(
                "chaos", kind, tick=self.driver._host_tick, **fields
            )

    # -- Restart with driver identity bookkeeping (no device reads) ----------
    def _restart(self, state, row: int, seed_rows):
        d = self.driver
        state = d._ops.join_row(state, row, seed_rows)
        from ..models.member import Member
        from ..sim.driver import row_address

        d.members[row] = Member(
            id=f"sim-{d._next_member_ordinal}", address=row_address(row)
        )
        d._next_member_ordinal += 1
        return state

    # -- the scenario loop ----------------------------------------------------
    def run(self, max_window: int = 32) -> dict:
        """Drive the scenario to its horizon; returns the structured report.
        Windows split at event boundaries and sentinel-check ticks, capped at
        ``max_window`` ticks each (the jit cache keys on window length, so a
        scenario reuses a handful of compiled window programs)."""
        d = self.driver
        self.max_window = max_window  # recorded for incident reconstruction
        horizon = self.spec.horizon
        check_every = self.spec.check_interval
        next_check = check_every if self._sent is not None else horizon + 1
        t = 0
        while True:
            # events due at t apply BEFORE the sentinel sample at t (a
            # restart's convergence obligation must be judged against the
            # post-restart view, and the same-tick heal against the healed
            # links)
            with d._lock:
                d.state, labels = self.timeline.apply_due(d.state, t)
            self.events_applied.extend((t, lab) for lab in labels)
            for lab in labels:
                self._publish("event_applied", event=lab, rel_tick=t)
            if self._sent is not None and (t >= next_check or t >= horizon):
                self._run_check()
                next_check = t + check_every
            if t >= horizon:
                break
            stops = [horizon, t + max_window, next_check]
            nt = self.timeline.next_tick()
            if nt is not None:
                stops.append(nt)
            stop = min(s for s in stops if s > t)
            d.step(stop - t)
            t = stop
            self.rel_tick = t
        self.done = True
        report = self.report()  # THE sync point: one coalesced readback
        self._attach_trace(report)
        self.last_report = report
        plane = getattr(d, "_telemetry", None)
        if plane is not None:
            # detection latencies -> histogram, completion -> bus; any
            # violation writes the flight-recorder post-mortem artifact
            dump = plane.ingest_chaos_report(report)
            if dump is not None:
                report["flight_dump"] = dump
        return report

    def _attach_trace(self, report: dict) -> None:
        """Resolve sentinel outcomes to sewn span trees (r10): every traced
        crash subject gets its probe-miss → suspect → DEAD lineage attached
        to its detection entry (violating or not — a PASSING detection's
        tree is how its latency is explained), and the report carries the
        full map under ``trace_spans``. One ring readback — this runs at
        the final-report sync point only."""
        tplane = getattr(self.driver, "_trace", None)
        if tplane is None:
            return
        from ..trace import spans as _spans

        events = tplane.events()
        trees = {}
        for det in (report.get("sentinels") or {}).get("detections", ()):
            row = det["row"]
            if row in tplane.spec.tracer_rows:
                tree = _spans.detection_tree(events, row)
                if tree is not None:
                    det["span_tree"] = tree
                    trees[int(row)] = tree
        report["trace_spans"] = trees
        if self._untraced_crash_rows:
            report["untraced_crash_rows"] = list(self._untraced_crash_rows)

    def _run_check(self) -> None:
        d = self.driver
        with d._lock:
            self._sent = self._check(d.state, self._sent, self._spec_dev)

    # -- reporting (the readback sites) ---------------------------------------
    def report(self) -> dict:
        """Structured scenario report. Reading it is a sync point (the
        sentinel accumulators come to host here)."""
        import os

        import jax

        events = list(self.events_applied)  # monitor thread vs sim appends
        rep = {
            "scenario": self.scenario.name,
            "armed": not self.done,
            "t0": self.t0,
            "horizon": self.spec.horizon,
            "ticks_run": self.rel_tick,
            # provenance stamps (the r13 backend-stamp rule, applied to the
            # chaos surface): which backend ran the scenario, on how many
            # host CPUs, over which absolute tick range
            "backend": jax.default_backend(),
            "host_cpus": os.cpu_count(),
            "tick_range": [self.t0, self.t0 + self.rel_tick],
            "events_applied": [{"tick": t, "event": lab} for t, lab in events],
        }
        if self._sent is not None:
            with self.driver._lock:
                sent_host = {k: np.asarray(v) for k, v in self._sent.items()}
            self.driver._note_readback(1)
            rep["sentinels"] = sentinel_report(
                sent_host, self.spec, final_tick=self.rel_tick
            )
            rep["violations"] = rep["sentinels"]["violations"]
            rep["ok"] = rep["sentinels"]["ok"]
        else:
            rep["sentinels"] = None
            rep["violations"] = 0
            rep["ok"] = True
        return rep

    def snapshot(self) -> dict:
        """Monitor-facing view (``GET /chaos`` / health_snapshot chaos
        section): the full report plus progress — safe to call from the
        monitor thread while the sim thread steps."""
        return self.report()


def run_driver_scenario(
    driver,
    scenario: Scenario,
    *,
    config=None,
    sentinels: bool = True,
    max_window: int = 32,
    trace: bool = False,
) -> dict:
    """Arm ``scenario`` on ``driver`` and run it to the horizon (the
    function behind ``SimDriver.run_scenario``). ``trace=True``
    auto-attaches the causal trace plane on the crashed rows (r10)."""
    runner = DriverChaosRunner(
        driver, scenario, config=config, sentinels=sentinels, trace=trace
    )
    return runner.run(max_window=max_window)


# ---------------------------------------------------------------------------
# NetworkEmulator runner (scalar / real-transport engine)
# ---------------------------------------------------------------------------


class EmulatorChaosRunner:
    """Replays the same scenario schedule onto per-node
    :class:`..transport.emulator.NetworkEmulator` instances.

    ``emulators[i]`` and ``addresses[i]`` are row ``i``'s emulator and wire
    address (the scenario's integer rows index this list). The caller owns
    time: call :meth:`advance_to` with the current scenario-relative tick
    (``elapsed_seconds / tick_interval`` for wall-clock engines) and every
    due action is applied. Crash maps to total network isolation and
    Restart to unblocking — the reference testlib's member-kill idiom for a
    process that stays alive."""

    def __init__(self, scenario: Scenario, emulators: Sequence,
                 addresses: Sequence[str], horizon: Optional[int] = None):
        if len(emulators) != len(addresses):
            raise ScenarioError("emulators and addresses must align by row")
        scenario.validate_rows(len(emulators))  # groups/pairs/rows/seeds
        # r14: the emulator's ONE default-outbound-settings slot per node
        # cannot stash/restore the way the device StateTimeline's storm
        # stash does, so a LossStorm overlapping a degraded event would
        # clobber whichever wrote the slot last — refuse loudly (the device
        # engines compose these correctly; run composed scenarios there)
        from .events import DEGRADED_EVENT_TYPES

        for ev in scenario.events:
            if isinstance(ev, DroppedRefute):
                raise ScenarioError(
                    "DroppedRefute manipulates the device view planes "
                    "(refute squashing); the emulator runner's members own "
                    "their real gossip state — run the scenario on the "
                    "dense engine"
                )
        deg = [e for e in scenario.events
               if isinstance(e, (SlowEpoch,) + DEGRADED_EVENT_TYPES)]
        storms = [e for e in scenario.events if isinstance(e, LossStorm)]
        for d in deg:
            d0, d1 = _window(d, "until")
            for s in storms:
                s0, s1 = _window(s, "until")
                if d0 < s1 and s0 < d1:
                    raise ScenarioError(
                        f"{type(d).__name__}{list(getattr(d, 'rows', ()))} "
                        "overlaps a LossStorm: the emulator runner's single "
                        "default-outbound slot cannot hold both — stagger "
                        "them, or run the composed scenario on a device "
                        "engine"
                    )
        self.scenario = scenario
        self._emus = list(emulators)
        self._addrs = list(addresses)
        self._steps = schedule(scenario, horizon=horizon)
        self._i = 0
        self.events_applied: List[Tuple[int, str]] = []

    def next_tick(self) -> Optional[int]:
        return self._steps[self._i].tick if self._i < len(self._steps) else None

    def advance_to(self, tick: int) -> List[str]:
        labels: List[str] = []
        while self._i < len(self._steps) and self._steps[self._i].tick <= tick:
            step = self._steps[self._i]
            self._i += 1
            self._apply(step)
            self.events_applied.append((step.tick, step.label))
            labels.append(step.label)
        return labels

    def report(self) -> dict:
        return {
            "scenario": self.scenario.name,
            "engine": "emulator",
            "events_applied": [
                {"tick": t, "event": lab} for t, lab in self.events_applied
            ],
            "pending": len(self._steps) - self._i,
        }

    def _apply(self, step: _Step) -> None:
        if step.kind == "partition_block":
            (groups,) = step.payload
            for a, b in itertools.combinations(groups, 2):
                self._block(a, b)
        elif step.kind == "partition_heal":
            (groups,) = step.payload
            for a, b in itertools.combinations(groups, 2):
                self._unblock(a, b)
        elif step.kind == "storm_start":
            (pct,) = step.payload
            for emu in self._emus:
                emu.set_default_outbound_settings(pct, 0.0)
        elif step.kind == "storm_end":
            for emu in self._emus:
                emu.set_default_outbound_settings(0.0, 0.0)
        elif step.kind == "flap_down":
            (pairs,) = step.payload
            for s, d in pairs:
                self._emus[s].block_outbound([self._addrs[d]])
        elif step.kind == "flap_up":
            (pairs,) = step.payload
            for s, d in pairs:
                self._emus[s].unblock_outbound([self._addrs[d]])
        elif step.kind == "slow_start":
            # NOTE (r14): the emulator maps degraded events COARSELY — a
            # per-destination entry overrides the node's default settings
            # entirely (loss AND delay travel together), so a flaky
            # member's sends toward a concurrently slow member carry the
            # slow delay at full reliability. Intersecting-cohort overlaps
            # are refused by schedule(); disjoint-cohort residue is this
            # documented approximation. The device engines model the loss
            # and delay planes independently.
            rows, delay = step.payload
            for r in rows:
                self._emus[r].set_default_outbound_settings(0.0, delay)
            for i, emu in enumerate(self._emus):
                if i not in rows:
                    for r in rows:
                        emu.set_outbound_settings(self._addrs[r], 0.0, delay)
        elif step.kind == "slow_end":
            (rows,) = step.payload
            for r in rows:
                self._emus[r].set_default_outbound_settings(0.0, 0.0)
            for i, emu in enumerate(self._emus):
                if i not in rows:
                    for r in rows:
                        emu.unblock_outbound([self._addrs[r]])
        elif step.kind == "asym_start":
            rows, pct, direction = step.payload
            if direction in ("in", "both"):
                for i, emu in enumerate(self._emus):
                    if i not in rows:
                        for r in rows:
                            emu.set_outbound_settings(self._addrs[r], pct, 0.0)
            if direction in ("out", "both"):
                for r in rows:
                    self._emus[r].set_default_outbound_settings(pct, 0.0)
        elif step.kind == "asym_end":
            rows, direction = step.payload
            if direction in ("in", "both"):
                for i, emu in enumerate(self._emus):
                    if i not in rows:
                        for r in rows:
                            emu.unblock_outbound([self._addrs[r]])
            if direction in ("out", "both"):
                for r in rows:
                    self._emus[r].set_default_outbound_settings(0.0, 0.0)
        elif step.kind == "zone_down":
            (rows,) = step.payload
            rest = [i for i in range(len(self._emus)) if i not in set(rows)]
            if rest:
                self._block(list(rows), rest)
        elif step.kind == "zone_up":
            (rows,) = step.payload
            rest = [i for i in range(len(self._emus)) if i not in set(rows)]
            if rest:
                self._unblock(list(rows), rest)
        elif step.kind == "slow_epoch_start":
            (delay,) = step.payload
            for emu in self._emus:
                emu.set_default_outbound_settings(0.0, delay)
        elif step.kind == "slow_epoch_end":
            for emu in self._emus:
                emu.set_default_outbound_settings(0.0, 0.0)
        elif step.kind == "crash":
            (rows,) = step.payload
            for r in rows:
                self._emus[r].block_all_outbound()
                self._emus[r].block_all_inbound()
        elif step.kind == "restart":
            rows, _seeds = step.payload
            for r in rows:
                self._emus[r].unblock_all_outbound()
                self._emus[r].unblock_all_inbound()

    def _block(self, a, b) -> None:
        addrs_a = [self._addrs[r] for r in a]
        addrs_b = [self._addrs[r] for r in b]
        for r in a:
            self._emus[r].block_outbound(addrs_b)
            self._emus[r].block_inbound(addrs_b)
        for r in b:
            self._emus[r].block_outbound(addrs_a)
            self._emus[r].block_inbound(addrs_a)

    def _unblock(self, a, b) -> None:
        addrs_a = [self._addrs[r] for r in a]
        addrs_b = [self._addrs[r] for r in b]
        for r in a:
            self._emus[r].unblock_outbound(addrs_b)
            self._emus[r].unblock_inbound(addrs_b)
        for r in b:
            self._emus[r].unblock_outbound(addrs_a)
            self._emus[r].unblock_inbound(addrs_a)
