"""The chaos scenario DSL: declarative fault events on a tick timeline.

Event ticks are RELATIVE to the tick at which the scenario is armed (so one
scenario file replays against any engine, at any point of a run). An event
with ``at=t`` is applied *between windows*, after the simulation has
completed tick ``t`` and before tick ``t+1`` runs — the same seam every host
mutator (``ops.state`` / ``ops.sparse`` / the ``NetworkEmulator`` controls)
already uses, so injection never perturbs an in-flight window.

The fault vocabulary mirrors the reference testlib's ``NetworkEmulator``
surface (loss percent, block/unblock, per-link settings) plus process-level
churn (crash = hard kill, restart = fresh identity on the same row — the
reference's restart-on-same-address-is-a-new-member rule).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple


class ScenarioError(ValueError):
    """A scenario that cannot be compiled (bad timeline / engine mismatch)."""


def _rows(seq) -> Tuple[int, ...]:
    return tuple(int(r) for r in seq)


@dataclass(frozen=True)
class Partition:
    """Symmetric network partition between member groups.

    ``groups`` is a sequence of row groups; traffic between any two distinct
    groups is blocked from tick ``at`` until ``heal_at`` (None = never heals
    inside the scenario). Rows in no group keep all their links — they are
    the bridge/bystander cohort the false-DEAD sentinel watches.
    """

    groups: Sequence[Sequence[int]]
    at: int
    heal_at: Optional[int] = None

    def __post_init__(self):
        object.__setattr__(self, "groups", tuple(_rows(g) for g in self.groups))
        if len(self.groups) < 2 or any(not g for g in self.groups):
            raise ScenarioError("Partition needs >= 2 non-empty groups")
        if self.heal_at is not None and self.heal_at <= self.at:
            raise ScenarioError("Partition.heal_at must be > at")


@dataclass(frozen=True)
class LossStorm:
    """Uniform loss floor of ``pct`` percent on EVERY link in [at, until).

    On dense-link engines the storm raises each link to at least ``pct``
    (existing blocks stay blocked); at ``until`` the pre-storm link matrix is
    restored and any partition/flap mutations made during the storm are
    replayed on top. On scalar-loss (lean sparse) engines the storm swaps the
    uniform loss scalar. On the emulator engine it becomes the default
    outbound settings.
    """

    pct: float
    at: int
    until: Optional[int] = None

    def __post_init__(self):
        if not (0.0 <= self.pct <= 100.0):
            raise ScenarioError("LossStorm.pct must be in [0, 100]")
        if self.until is not None and self.until <= self.at:
            raise ScenarioError("LossStorm.until must be > at")


@dataclass(frozen=True)
class LinkFlap:
    """Directed links that toggle blocked/clear every ``period`` ticks.

    ``pairs`` are (src, dst) row pairs; the link is DOWN during even
    half-periods starting at ``at`` and restored to loss 0 during odd ones,
    until ``until`` (required bounded — an unbounded flap has no horizon),
    ending clear.
    """

    pairs: Sequence[Tuple[int, int]]
    period: int
    at: int = 0
    until: Optional[int] = None

    def __post_init__(self):
        object.__setattr__(
            self, "pairs", tuple((int(s), int(d)) for s, d in self.pairs)
        )
        if not self.pairs:
            raise ScenarioError("LinkFlap needs at least one (src, dst) pair")
        if self.period < 1:
            raise ScenarioError("LinkFlap.period must be >= 1")
        if self.until is not None and self.until <= self.at:
            raise ScenarioError("LinkFlap.until must be > at")


@dataclass(frozen=True)
class SlowMember:
    """Members that are SLOW but alive (r14): every link to/from ``rows``
    gains ``mean_delay_ticks`` of exponential-mean delay in [at, until).

    The Lifeguard false-positive archetype: a slow member's probe round
    trips start missing the static ping timeout, so a static detector
    declares it DEAD while it is still running. Needs the dense-link
    engine with ``params.delay_slots > 0`` (the delay model); ``until``
    clears the touched links back to zero delay."""

    rows: Sequence[int]
    mean_delay_ticks: float
    at: int
    until: Optional[int] = None

    def __post_init__(self):
        object.__setattr__(self, "rows", _rows(self.rows))
        if not self.rows:
            raise ScenarioError("SlowMember needs at least one row")
        if self.mean_delay_ticks <= 0:
            raise ScenarioError("SlowMember.mean_delay_ticks must be > 0")
        if self.until is not None and self.until <= self.at:
            raise ScenarioError("SlowMember.until must be > at")


@dataclass(frozen=True)
class AsymmetricLoss:
    """Lossy-but-alive members (r14): directed loss floor of ``pct``
    percent on the links INTO ``rows`` (``direction="in"``), OUT of them
    (``"out"``), or both, in [at, until).

    ``"in"`` starves the member of probes and ACK requests — observers'
    probes fail and the member looks dead from outside. ``"out"`` makes
    the member a degraded OBSERVER — its own probes fail, so a static
    detector lets it spray false suspicions of healthy peers. Dense-link
    engines only; ``until`` clears the touched links (to the active
    storm's floor while one is running, like every link mutation)."""

    rows: Sequence[int]
    pct: float
    at: int
    until: Optional[int] = None
    direction: str = "in"

    def __post_init__(self):
        object.__setattr__(self, "rows", _rows(self.rows))
        if not self.rows:
            raise ScenarioError("AsymmetricLoss needs at least one row")
        if not (0.0 < self.pct <= 100.0):
            raise ScenarioError("AsymmetricLoss.pct must be in (0, 100]")
        if self.direction not in ("in", "out", "both"):
            raise ScenarioError(
                "AsymmetricLoss.direction must be 'in', 'out', or 'both'"
            )
        if self.until is not None and self.until <= self.at:
            raise ScenarioError("AsymmetricLoss.until must be > at")


@dataclass(frozen=True)
class FlakyObserver:
    """A degraded observer (r14): outbound loss floor of ``pct`` percent on
    every link OUT of ``rows`` in [at, until) — sugar for
    ``AsymmetricLoss(direction="out")``, named for the failure mode it
    exercises: the member whose own probes keep failing and who therefore
    accuses healthy peers. The adaptive plane's local-health score is the
    defense (its lh climbs, stretching the suspicions it ages)."""

    rows: Sequence[int]
    pct: float
    at: int
    until: Optional[int] = None

    def __post_init__(self):
        object.__setattr__(self, "rows", _rows(self.rows))
        if not self.rows:
            raise ScenarioError("FlakyObserver needs at least one row")
        if not (0.0 < self.pct <= 100.0):
            raise ScenarioError("FlakyObserver.pct must be in (0, 100]")
        if self.until is not None and self.until <= self.at:
            raise ScenarioError("FlakyObserver.until must be > at")


@dataclass(frozen=True)
class Crash:
    """Hard-kill ``rows`` at tick ``at`` (no goodbye; peers must detect)."""

    rows: Sequence[int]
    at: int

    def __post_init__(self):
        object.__setattr__(self, "rows", _rows(self.rows))
        if not self.rows:
            raise ScenarioError("Crash needs at least one row")


@dataclass(frozen=True)
class Restart:
    """Re-activate ``rows`` at tick ``at`` as FRESH identities (epoch bump —
    the restart-is-a-new-member rule), bootstrapping via ``seed_rows``."""

    rows: Sequence[int]
    at: int
    seed_rows: Sequence[int] = (0,)

    def __post_init__(self):
        object.__setattr__(self, "rows", _rows(self.rows))
        object.__setattr__(self, "seed_rows", _rows(self.seed_rows))
        if not self.rows:
            raise ScenarioError("Restart needs at least one row")


EVENT_TYPES = (
    Partition, LossStorm, LinkFlap, Crash, Restart,
    SlowMember, AsymmetricLoss, FlakyObserver,
)

#: the r14 loss-adversarial family: events that DEGRADE members without
#: killing them — the false-positive sentinel's watch cohort
DEGRADED_EVENT_TYPES = (SlowMember, AsymmetricLoss, FlakyObserver)


@dataclass(frozen=True)
class Scenario:
    """A named, validated fault timeline + sentinel budgets.

    ``horizon`` is the total tick span the scenario runs for (None = derived:
    last event boundary plus the convergence budget). ``detect_budget`` /
    ``converge_budget`` override the protocol-math defaults (0/None = auto
    from the engine params — see :func:`.sentinels.build_spec`), and
    ``check_interval`` sets the sentinel sampling cadence in ticks (sentinel
    facts are latching/monotone, so sampling is sound — see sentinels.py).

    ``fp_watch_rows`` (r14) adds explicit rows to the FALSE-POSITIVE
    sentinel's watch cohort — by default it watches the degraded-but-alive
    rows of SlowMember / AsymmetricLoss / FlakyObserver events (minus any
    row a Crash also hits). A watched row tombstoned by any up observer is
    a false positive; ``fp_enforce=False`` records the count without
    counting it as a violation (the static-timeout CONTROL arm of the r14
    certification is expected to violate — documented, not hidden).
    """

    name: str
    events: Sequence
    horizon: Optional[int] = None
    detect_budget: Optional[int] = None
    converge_budget: Optional[int] = None
    check_interval: Optional[int] = None
    fp_watch_rows: Sequence[int] = ()
    fp_enforce: bool = True

    def __post_init__(self):
        object.__setattr__(self, "events", tuple(self.events))
        object.__setattr__(self, "fp_watch_rows", _rows(self.fp_watch_rows))
        for ev in self.events:
            if not isinstance(ev, EVENT_TYPES):
                raise ScenarioError(f"unknown scenario event {ev!r}")
            if ev.at < 0:
                raise ScenarioError(f"event {ev} starts before the arm tick")
        if self.horizon is not None and self.horizon < 1:
            raise ScenarioError("Scenario.horizon must be >= 1")

    # -- derived views -------------------------------------------------------
    def referenced_rows(self) -> set:
        """Every row any event names: crash/restart targets + their seeds,
        partition group members, flap endpoints, degraded/fp-watch rows."""
        rows: set = set(self.fp_watch_rows)
        for ev in self.events:
            for attr in ("rows", "seed_rows"):
                rows.update(getattr(ev, attr, ()))
            for g in getattr(ev, "groups", ()):
                rows.update(g)
            for s, d in getattr(ev, "pairs", ()):
                rows.update((s, d))
        return rows

    def degraded_rows(self) -> set:
        """Rows the r14 loss-adversarial events degrade WITHOUT killing
        (SlowMember / AsymmetricLoss / FlakyObserver targets, minus rows a
        Crash also hits) — the false-positive sentinel's default watch
        cohort: these members stay alive the whole scenario, so a DEAD
        verdict about any of them is by construction a false positive."""
        deg: set = set()
        crashed: set = set()
        for ev in self.events:
            if isinstance(ev, DEGRADED_EVENT_TYPES):
                deg.update(ev.rows)
            elif isinstance(ev, Crash):
                crashed.update(ev.rows)
        return deg - crashed

    def validate_rows(self, capacity: int) -> None:
        """Fail FAST on rows outside ``[0, capacity)`` — a silent JAX
        clamp/no-op would otherwise inject nothing and make the sentinels
        watch the wrong (healthy) row."""
        bad = sorted(r for r in self.referenced_rows()
                     if not 0 <= r < capacity)
        if bad:
            raise ScenarioError(
                f"scenario {self.name!r} references rows {bad} outside the "
                f"{capacity}-row engine"
            )

    def last_event_tick(self) -> int:
        """Last tick at which any timeline action fires (0 when eventless)."""
        last = 0
        for ev in self.events:
            last = max(last, ev.at)
            for attr in ("heal_at", "until"):
                v = getattr(ev, attr, None)
                if v is not None:
                    last = max(last, v)
        return last

    def fault_touched_rows(
        self, capacity: int, loss_storm_immunity_pct: float = 50.0
    ) -> set:
        """Rows any event may plausibly fault: crash/restart targets,
        partition group members, flap endpoints — and EVERY row while a
        ``LossStorm`` at or above ``loss_storm_immunity_pct`` is scripted
        (heavy uniform loss can legitimately suspect anyone; below the
        threshold the no-false-DEAD guarantee is expected to hold). The
        complement is the never-faulted cohort the false-DEAD sentinel
        protects."""
        touched: set = set()
        for ev in self.events:
            if isinstance(ev, (Crash, Restart)):
                touched.update(ev.rows)
            elif isinstance(ev, Partition):
                for g in ev.groups:
                    touched.update(g)
            elif isinstance(ev, LinkFlap):
                for s, d in ev.pairs:
                    touched.update((s, d))
            elif isinstance(ev, LossStorm) and ev.pct >= loss_storm_immunity_pct:
                touched.update(range(capacity))
            elif isinstance(ev, DEGRADED_EVENT_TYPES):
                # a degraded member is both suspectable (its links fail)
                # and a degraded OBSERVER (its own probes fail — it can
                # falsely suspect anyone), so the legacy no-false-DEAD
                # vouching covers nobody while these run; the r14
                # false-positive sentinel is the guarantee for this family
                touched.update(range(capacity))
        return {r for r in touched if 0 <= r < capacity}

    def replace(self, **kw) -> "Scenario":
        return dataclasses.replace(self, **kw)
