"""The chaos scenario DSL: declarative fault events on a tick timeline.

Event ticks are RELATIVE to the tick at which the scenario is armed (so one
scenario file replays against any engine, at any point of a run). An event
with ``at=t`` is applied *between windows*, after the simulation has
completed tick ``t`` and before tick ``t+1`` runs — the same seam every host
mutator (``ops.state`` / ``ops.sparse`` / the ``NetworkEmulator`` controls)
already uses, so injection never perturbs an in-flight window.

The fault vocabulary mirrors the reference testlib's ``NetworkEmulator``
surface (loss percent, block/unblock, per-link settings) plus process-level
churn (crash = hard kill, restart = fresh identity on the same row — the
reference's restart-on-same-address-is-a-new-member rule).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple


class ScenarioError(ValueError):
    """A scenario that cannot be compiled (bad timeline / engine mismatch)."""


def _rows(seq) -> Tuple[int, ...]:
    return tuple(int(r) for r in seq)


@dataclass(frozen=True)
class Partition:
    """Symmetric network partition between member groups.

    ``groups`` is a sequence of row groups; traffic between any two distinct
    groups is blocked from tick ``at`` until ``heal_at`` (None = never heals
    inside the scenario). Rows in no group keep all their links — they are
    the bridge/bystander cohort the false-DEAD sentinel watches.
    """

    groups: Sequence[Sequence[int]]
    at: int
    heal_at: Optional[int] = None

    def __post_init__(self):
        object.__setattr__(self, "groups", tuple(_rows(g) for g in self.groups))
        if len(self.groups) < 2 or any(not g for g in self.groups):
            raise ScenarioError("Partition needs >= 2 non-empty groups")
        if self.heal_at is not None and self.heal_at <= self.at:
            raise ScenarioError("Partition.heal_at must be > at")


@dataclass(frozen=True)
class LossStorm:
    """Uniform loss floor of ``pct`` percent on EVERY link in [at, until).

    On dense-link engines the storm raises each link to at least ``pct``
    (existing blocks stay blocked); at ``until`` the pre-storm link matrix is
    restored and any partition/flap mutations made during the storm are
    replayed on top. On scalar-loss (lean sparse) engines the storm swaps the
    uniform loss scalar. On the emulator engine it becomes the default
    outbound settings.
    """

    pct: float
    at: int
    until: Optional[int] = None

    def __post_init__(self):
        if not (0.0 <= self.pct <= 100.0):
            raise ScenarioError("LossStorm.pct must be in [0, 100]")
        if self.until is not None and self.until <= self.at:
            raise ScenarioError("LossStorm.until must be > at")


@dataclass(frozen=True)
class LinkFlap:
    """Directed links that toggle blocked/clear every ``period`` ticks.

    ``pairs`` are (src, dst) row pairs; the link is DOWN during even
    half-periods starting at ``at`` and restored to loss 0 during odd ones,
    until ``until`` (required bounded — an unbounded flap has no horizon),
    ending clear.
    """

    pairs: Sequence[Tuple[int, int]]
    period: int
    at: int = 0
    until: Optional[int] = None

    def __post_init__(self):
        object.__setattr__(
            self, "pairs", tuple((int(s), int(d)) for s, d in self.pairs)
        )
        if not self.pairs:
            raise ScenarioError("LinkFlap needs at least one (src, dst) pair")
        if self.period < 1:
            raise ScenarioError("LinkFlap.period must be >= 1")
        if self.until is not None and self.until <= self.at:
            raise ScenarioError("LinkFlap.until must be > at")


@dataclass(frozen=True)
class SlowMember:
    """Members that are SLOW but alive (r14): every link to/from ``rows``
    gains ``mean_delay_ticks`` of exponential-mean delay in [at, until).

    The Lifeguard false-positive archetype: a slow member's probe round
    trips start missing the static ping timeout, so a static detector
    declares it DEAD while it is still running. Needs the dense-link
    engine with ``params.delay_slots > 0`` (the delay model); ``until``
    clears the touched links back to zero delay."""

    rows: Sequence[int]
    mean_delay_ticks: float
    at: int
    until: Optional[int] = None

    def __post_init__(self):
        object.__setattr__(self, "rows", _rows(self.rows))
        if not self.rows:
            raise ScenarioError("SlowMember needs at least one row")
        if self.mean_delay_ticks <= 0:
            raise ScenarioError("SlowMember.mean_delay_ticks must be > 0")
        if self.until is not None and self.until <= self.at:
            raise ScenarioError("SlowMember.until must be > at")


@dataclass(frozen=True)
class AsymmetricLoss:
    """Lossy-but-alive members (r14): directed loss floor of ``pct``
    percent on the links INTO ``rows`` (``direction="in"``), OUT of them
    (``"out"``), or both, in [at, until).

    ``"in"`` starves the member of probes and ACK requests — observers'
    probes fail and the member looks dead from outside. ``"out"`` makes
    the member a degraded OBSERVER — its own probes fail, so a static
    detector lets it spray false suspicions of healthy peers. Dense-link
    engines only; ``until`` clears the touched links (to the active
    storm's floor while one is running, like every link mutation)."""

    rows: Sequence[int]
    pct: float
    at: int
    until: Optional[int] = None
    direction: str = "in"

    def __post_init__(self):
        object.__setattr__(self, "rows", _rows(self.rows))
        if not self.rows:
            raise ScenarioError("AsymmetricLoss needs at least one row")
        if not (0.0 < self.pct <= 100.0):
            raise ScenarioError("AsymmetricLoss.pct must be in (0, 100]")
        if self.direction not in ("in", "out", "both"):
            raise ScenarioError(
                "AsymmetricLoss.direction must be 'in', 'out', or 'both'"
            )
        if self.until is not None and self.until <= self.at:
            raise ScenarioError("AsymmetricLoss.until must be > at")


@dataclass(frozen=True)
class FlakyObserver:
    """A degraded observer (r14): outbound loss floor of ``pct`` percent on
    every link OUT of ``rows`` in [at, until) — sugar for
    ``AsymmetricLoss(direction="out")``, named for the failure mode it
    exercises: the member whose own probes keep failing and who therefore
    accuses healthy peers. The adaptive plane's local-health score is the
    defense (its lh climbs, stretching the suspicions it ages)."""

    rows: Sequence[int]
    pct: float
    at: int
    until: Optional[int] = None

    def __post_init__(self):
        object.__setattr__(self, "rows", _rows(self.rows))
        if not self.rows:
            raise ScenarioError("FlakyObserver needs at least one row")
        if not (0.0 < self.pct <= 100.0):
            raise ScenarioError("FlakyObserver.pct must be in (0, 100]")
        if self.until is not None and self.until <= self.at:
            raise ScenarioError("FlakyObserver.until must be > at")


@dataclass(frozen=True)
class Crash:
    """Hard-kill ``rows`` at tick ``at`` (no goodbye; peers must detect)."""

    rows: Sequence[int]
    at: int

    def __post_init__(self):
        object.__setattr__(self, "rows", _rows(self.rows))
        if not self.rows:
            raise ScenarioError("Crash needs at least one row")


@dataclass(frozen=True)
class Restart:
    """Re-activate ``rows`` at tick ``at`` as FRESH identities (epoch bump —
    the restart-is-a-new-member rule), bootstrapping via ``seed_rows``."""

    rows: Sequence[int]
    at: int
    seed_rows: Sequence[int] = (0,)

    def __post_init__(self):
        object.__setattr__(self, "rows", _rows(self.rows))
        object.__setattr__(self, "seed_rows", _rows(self.seed_rows))
        if not self.rows:
            raise ScenarioError("Restart needs at least one row")


@dataclass(frozen=True)
class ZoneOutage:
    """Correlated group failure (r18): the whole ``rows`` zone loses
    connectivity to EVERY other member in [at, until) — a rack/AZ cut.

    Unlike :class:`Partition` the complement is implicit ("everyone else"),
    so the event compiles against any capacity without naming the rest of
    the cluster; there are no bystanders. Rides the dense link planes, and
    the pview ``GROUP_PARTITIONS`` capability on the 1M-member engine.
    ``until`` (None = never heals inside the scenario) restores the cut
    links to clear (or the active storm's floor, like every heal).
    """

    rows: Sequence[int]
    at: int
    until: Optional[int] = None

    def __post_init__(self):
        object.__setattr__(self, "rows", _rows(self.rows))
        if not self.rows:
            raise ScenarioError("ZoneOutage needs at least one row")
        if self.until is not None and self.until <= self.at:
            raise ScenarioError("ZoneOutage.until must be > at")


@dataclass(frozen=True)
class ChurnStorm:
    """Batched crash/restart waves (r18): ``rows`` split into ``waves``
    contiguous chunks; chunk ``k`` hard-crashes at ``at + k*period`` and
    restarts (fresh identity, epoch bump) ``down_for`` ticks later via
    ``seed_rows`` — the scalecube testlib rolling-churn archetype.

    Waves may overlap (``down_for > period`` keeps several chunks down at
    once), which is why ``seed_rows`` must be disjoint from ``rows``: the
    bootstrap contact has to stay up through the whole storm.
    """

    rows: Sequence[int]
    at: int
    waves: int = 2
    period: int = 8
    down_for: int = 4
    seed_rows: Sequence[int] = (0,)

    def __post_init__(self):
        object.__setattr__(self, "rows", _rows(self.rows))
        object.__setattr__(self, "seed_rows", _rows(self.seed_rows))
        if not self.rows:
            raise ScenarioError("ChurnStorm needs at least one row")
        if self.waves < 1:
            raise ScenarioError("ChurnStorm.waves must be >= 1")
        if len(self.rows) < self.waves:
            raise ScenarioError(
                "ChurnStorm needs at least one row per wave "
                f"({len(self.rows)} rows < {self.waves} waves)"
            )
        if self.period < 1:
            raise ScenarioError("ChurnStorm.period must be >= 1")
        if self.down_for < 1:
            raise ScenarioError("ChurnStorm.down_for must be >= 1")
        if set(self.rows) & set(self.seed_rows):
            raise ScenarioError(
                "ChurnStorm.seed_rows must be disjoint from rows (the "
                "bootstrap contact must survive the storm)"
            )

    def wave_schedule(self) -> Tuple[Tuple[int, int, Tuple[int, ...]], ...]:
        """``(crash_tick, restart_tick, chunk_rows)`` per wave, in order."""
        n = len(self.rows)
        per = -(-n // self.waves)  # ceil division
        out = []
        for k in range(self.waves):
            chunk = self.rows[k * per:(k + 1) * per]
            if not chunk:
                break
            t = self.at + k * self.period
            out.append((t, t + self.down_for, chunk))
        return tuple(out)

    def last_tick(self) -> int:
        return max(r for _, r, _ in self.wave_schedule())


@dataclass(frozen=True)
class SlowEpoch:
    """Time-boxed slow-network epoch (r18): EVERY link gains
    ``mean_delay_ticks`` of exponential-mean delay in [at, until) — the
    cluster-wide analogue of :class:`SlowMember` (whole-fabric congestion,
    not one slow host). Needs the dense delay model (``delay_slots > 0``);
    ``until`` is required (an unbounded slow epoch has no horizon) and
    restores every link to zero delay.
    """

    mean_delay_ticks: float
    at: int
    until: int

    def __post_init__(self):
        if self.mean_delay_ticks <= 0:
            raise ScenarioError("SlowEpoch.mean_delay_ticks must be > 0")
        if self.until is None or self.until <= self.at:
            raise ScenarioError("SlowEpoch.until must be > at")


@dataclass(frozen=True)
class DroppedRefute:
    """Byzantine-adjacent refute suppression (r18): in [at, until) every
    self-refutation ``rows`` issue is squashed before it can disseminate —
    the member keeps running (it probes, acks, gossips other rumors) but
    its alive-again counter-evidence never leaves the host, as if an
    adversary dropped exactly those packets.

    Mechanically the timeline rewinds each row's OWN self-record to the
    strongest record the rest of the cluster holds whenever the row has
    refuted (inc-bumped over) a SUSPECT/DEAD verdict, every tick of the
    window — exercising the r14 suspicion/refutation race from the losing
    side. The rows stay alive, so any DEAD verdict about them inside the
    window is a *true* suppression casualty, not a detector bug: they join
    the false-positive watch cohort only via explicit ``fp_watch_rows``.
    ``until`` is required; after it, normal refutation resumes and the rows
    must converge back to ALIVE (the heal obligation the sentinels check).
    Dense engines only (needs the [N, N] view planes + changed_at).
    """

    rows: Sequence[int]
    at: int
    until: int

    def __post_init__(self):
        object.__setattr__(self, "rows", _rows(self.rows))
        if not self.rows:
            raise ScenarioError("DroppedRefute needs at least one row")
        if self.until is None or self.until <= self.at:
            raise ScenarioError("DroppedRefute.until must be > at")


EVENT_TYPES = (
    Partition, LossStorm, LinkFlap, Crash, Restart,
    SlowMember, AsymmetricLoss, FlakyObserver,
    ZoneOutage, ChurnStorm, SlowEpoch, DroppedRefute,
)

#: the r14 loss-adversarial family: events that DEGRADE members without
#: killing them — the false-positive sentinel's watch cohort
DEGRADED_EVENT_TYPES = (SlowMember, AsymmetricLoss, FlakyObserver)


@dataclass(frozen=True)
class Scenario:
    """A named, validated fault timeline + sentinel budgets.

    ``horizon`` is the total tick span the scenario runs for (None = derived:
    last event boundary plus the convergence budget). ``detect_budget`` /
    ``converge_budget`` override the protocol-math defaults (0/None = auto
    from the engine params — see :func:`.sentinels.build_spec`), and
    ``check_interval`` sets the sentinel sampling cadence in ticks (sentinel
    facts are latching/monotone, so sampling is sound — see sentinels.py).

    ``fp_watch_rows`` (r14) adds explicit rows to the FALSE-POSITIVE
    sentinel's watch cohort — by default it watches the degraded-but-alive
    rows of SlowMember / AsymmetricLoss / FlakyObserver events (minus any
    row a Crash also hits). A watched row tombstoned by any up observer is
    a false positive; ``fp_enforce=False`` records the count without
    counting it as a violation (the static-timeout CONTROL arm of the r14
    certification is expected to violate — documented, not hidden).
    """

    name: str
    events: Sequence
    horizon: Optional[int] = None
    detect_budget: Optional[int] = None
    converge_budget: Optional[int] = None
    check_interval: Optional[int] = None
    fp_watch_rows: Sequence[int] = ()
    fp_enforce: bool = True

    def __post_init__(self):
        object.__setattr__(self, "events", tuple(self.events))
        object.__setattr__(self, "fp_watch_rows", _rows(self.fp_watch_rows))
        for ev in self.events:
            if not isinstance(ev, EVENT_TYPES):
                raise ScenarioError(f"unknown scenario event {ev!r}")
            if ev.at < 0:
                raise ScenarioError(f"event {ev} starts before the arm tick")
        if self.horizon is not None and self.horizon < 1:
            raise ScenarioError("Scenario.horizon must be >= 1")

    # -- derived views -------------------------------------------------------
    def referenced_rows(self) -> set:
        """Every row any event names: crash/restart targets + their seeds,
        partition group members, flap endpoints, degraded/fp-watch rows."""
        rows: set = set(self.fp_watch_rows)
        for ev in self.events:
            for attr in ("rows", "seed_rows"):
                rows.update(getattr(ev, attr, ()))
            for g in getattr(ev, "groups", ()):
                rows.update(g)
            for s, d in getattr(ev, "pairs", ()):
                rows.update((s, d))
        return rows

    def degraded_rows(self) -> set:
        """Rows the r14 loss-adversarial events degrade WITHOUT killing
        (SlowMember / AsymmetricLoss / FlakyObserver targets, minus rows a
        Crash also hits) — the false-positive sentinel's default watch
        cohort: these members stay alive the whole scenario, so a DEAD
        verdict about any of them is by construction a false positive."""
        deg: set = set()
        crashed: set = set()
        for ev in self.events:
            if isinstance(ev, DEGRADED_EVENT_TYPES):
                deg.update(ev.rows)
            elif isinstance(ev, Crash):
                crashed.update(ev.rows)
        return deg - crashed

    def validate_rows(self, capacity: int) -> None:
        """Fail FAST on rows outside ``[0, capacity)`` — a silent JAX
        clamp/no-op would otherwise inject nothing and make the sentinels
        watch the wrong (healthy) row."""
        bad = sorted(r for r in self.referenced_rows()
                     if not 0 <= r < capacity)
        if bad:
            raise ScenarioError(
                f"scenario {self.name!r} references rows {bad} outside the "
                f"{capacity}-row engine"
            )

    def last_event_tick(self) -> int:
        """Last tick at which any timeline action fires (0 when eventless)."""
        last = 0
        for ev in self.events:
            last = max(last, ev.at)
            for attr in ("heal_at", "until"):
                v = getattr(ev, attr, None)
                if v is not None:
                    last = max(last, v)
            if isinstance(ev, ChurnStorm):
                last = max(last, ev.last_tick())
        return last

    def fault_touched_rows(
        self, capacity: int, loss_storm_immunity_pct: float = 50.0
    ) -> set:
        """Rows any event may plausibly fault: crash/restart targets,
        partition group members, flap endpoints — and EVERY row while a
        ``LossStorm`` at or above ``loss_storm_immunity_pct`` is scripted
        (heavy uniform loss can legitimately suspect anyone; below the
        threshold the no-false-DEAD guarantee is expected to hold). The
        complement is the never-faulted cohort the false-DEAD sentinel
        protects."""
        touched: set = set()
        for ev in self.events:
            if isinstance(ev, (Crash, Restart, ChurnStorm, DroppedRefute)):
                # a DroppedRefute row can legitimately age to DEAD while its
                # refutes are suppressed — that is the fault, not a detector
                # bug, so the false-DEAD sentinel must not vouch for it
                touched.update(ev.rows)
            elif isinstance(ev, Partition):
                for g in ev.groups:
                    touched.update(g)
            elif isinstance(ev, (ZoneOutage, SlowEpoch)):
                # a zone cut severs links on BOTH sides (no bystanders), and
                # a slow epoch delays every link — nobody is vouched-for
                touched.update(range(capacity))
            elif isinstance(ev, LinkFlap):
                for s, d in ev.pairs:
                    touched.update((s, d))
            elif isinstance(ev, LossStorm) and ev.pct >= loss_storm_immunity_pct:
                touched.update(range(capacity))
            elif isinstance(ev, DEGRADED_EVENT_TYPES):
                # a degraded member is both suspectable (its links fail)
                # and a degraded OBSERVER (its own probes fail — it can
                # falsely suspect anyone), so the legacy no-false-DEAD
                # vouching covers nobody while these run; the r14
                # false-positive sentinel is the guarantee for this family
                touched.update(range(capacity))
        return {r for r in touched if 0 <= r < capacity}

    def replace(self, **kw) -> "Scenario":
        return dataclasses.replace(self, **kw)


# -- (de)serialization --------------------------------------------------------
# Flight dumps (telemetry/flight.py schema >= 2) embed the armed scenario so
# replay.py can rebuild it without inference. Events round-trip through plain
# dicts: {"type": <class name>, ...fields} — JSON-safe, no pickle.

_EVENT_BY_NAME = {cls.__name__: cls for cls in EVENT_TYPES}


def event_to_dict(ev) -> dict:
    """JSON-safe dict for one timeline event (round-trips via
    :func:`event_from_dict`)."""
    if not isinstance(ev, EVENT_TYPES):
        raise ScenarioError(f"cannot serialize unknown event {ev!r}")
    doc = dataclasses.asdict(ev)
    doc["type"] = type(ev).__name__
    return doc


def event_from_dict(doc: dict):
    """Inverse of :func:`event_to_dict`; raises ``ScenarioError`` on an
    unknown type name or bad fields (future-vocabulary dumps fail LOUDLY)."""
    if not isinstance(doc, dict) or "type" not in doc:
        raise ScenarioError(f"malformed event doc {doc!r}")
    cls = _EVENT_BY_NAME.get(doc["type"])
    if cls is None:
        raise ScenarioError(
            f"unknown event type {doc['type']!r} (from a newer fault "
            f"vocabulary?) — known: {sorted(_EVENT_BY_NAME)}"
        )
    kw = {k: v for k, v in doc.items() if k != "type"}
    try:
        return cls(**kw)
    except TypeError as e:
        raise ScenarioError(f"bad fields for {doc['type']}: {e}") from e


def scenario_to_dict(scenario: "Scenario") -> dict:
    """JSON-safe dict for a full scenario (events + budgets)."""
    return {
        "name": scenario.name,
        "events": [event_to_dict(ev) for ev in scenario.events],
        "horizon": scenario.horizon,
        "detect_budget": scenario.detect_budget,
        "converge_budget": scenario.converge_budget,
        "check_interval": scenario.check_interval,
        "fp_watch_rows": list(scenario.fp_watch_rows),
        "fp_enforce": scenario.fp_enforce,
    }


def scenario_from_dict(doc: dict) -> "Scenario":
    """Inverse of :func:`scenario_to_dict`."""
    if not isinstance(doc, dict) or "name" not in doc or "events" not in doc:
        raise ScenarioError(f"malformed scenario doc: {sorted(doc) if isinstance(doc, dict) else doc!r}")
    return Scenario(
        name=doc["name"],
        events=tuple(event_from_dict(e) for e in doc["events"]),
        horizon=doc.get("horizon"),
        detect_budget=doc.get("detect_budget"),
        converge_budget=doc.get("converge_budget"),
        check_interval=doc.get("check_interval"),
        fp_watch_rows=tuple(doc.get("fp_watch_rows", ())),
        fp_enforce=bool(doc.get("fp_enforce", True)),
    )
