"""Chaos scenario engine: scripted fault timelines + SWIM invariant sentinels.

A :class:`Scenario` is a declarative timeline of fault events (partitions,
loss storms, link flaps, crashes, restarts) that compiles into between-window
mutations of the device-resident link/status planes for every simulated
engine (dense, sparse, mesh-sharded — :class:`DriverChaosRunner` /
``SimDriver.run_scenario``) and into :class:`..transport.NetworkEmulator`
settings for the scalar/real-transport engine
(:class:`EmulatorChaosRunner`) — one scenario file exercises all four code
paths.

Alongside injection, invariant *sentinels* (:mod:`.sentinels`) evaluate the
protocol guarantees the related rumor-spreading literature frames (PAPERS.md:
"Simple and Optimal Randomized Fault-Tolerant Rumor Spreading", "Robust and
Tuneable Family of Gossiping Algorithms"): no false-DEAD of a never-faulted
member, bounded detection latency after a crash, view re-convergence within a
budget after a heal, and incarnation/key monotonicity. Sentinel reductions
accumulate ON DEVICE through the r6 deferred-readback machinery — an armed
chaos engine adds zero per-window device→host transfers; violations surface
at the sync points (``SimDriver.health_snapshot``, ``GET /chaos``, the final
scenario report).
"""

from .events import (
    AsymmetricLoss,
    Crash,
    FlakyObserver,
    LinkFlap,
    LossStorm,
    Partition,
    Restart,
    Scenario,
    SlowMember,
)
from .engine import (
    DriverChaosRunner,
    EmulatorChaosRunner,
    ScenarioError,
    StateTimeline,
    run_driver_scenario,
)
from .sentinels import (
    SentinelSpec,
    build_spec,
    dissemination_budget_scale,
    init_sentinel_state,
    sentinel_report,
)
from .shifting import (
    SHIFTING_FAMILY,
    ShiftingScenario,
    loss_storm_midrun,
    migrating_asym_loss,
    wan_zone_degrade,
)


def spread_certifier(*args, **kwargs):
    """r13 spread-time certification sweep (re-exported from
    :mod:`..dissemination.certify`): measures each strategy's rumor
    spread-time distribution per topology, checks it against the cited
    theory bound, and — given ``bus=`` a telemetry bus — publishes the
    verdicts onto the same ordered event stream the scenario events ride."""
    from ..dissemination.certify import spread_certifier as _sc

    return _sc(*args, **kwargs)


__all__ = [
    "Partition",
    "LossStorm",
    "LinkFlap",
    "Crash",
    "Restart",
    "SlowMember",
    "AsymmetricLoss",
    "FlakyObserver",
    "Scenario",
    "ScenarioError",
    "StateTimeline",
    "DriverChaosRunner",
    "EmulatorChaosRunner",
    "run_driver_scenario",
    "SentinelSpec",
    "build_spec",
    "dissemination_budget_scale",
    "init_sentinel_state",
    "sentinel_report",
    "spread_certifier",
    "ShiftingScenario",
    "SHIFTING_FAMILY",
    "loss_storm_midrun",
    "wan_zone_degrade",
    "migrating_asym_loss",
]
