"""Shifting-conditions scenario family (r16): chaos whose CONDITIONS move.

Every scenario the chaos plane has certified so far holds its adversary
fixed for the whole run — one storm level, one degraded cohort, one
topology. A production cluster's faults SHIFT: a loss storm arrives
mid-run, a WAN zone degrades and recovers, asymmetric loss migrates
between regions as routing changes. Fault-tolerant rumor-spreading theory
(arXiv:1209.6158) gives per-condition optimal protocol settings, which is
exactly why shifting conditions are the closed-loop controller's
certification adversary (``control.py``): no static knob setting is right
for both phases, so the controller must TRACK the condition.

Each builder returns a :class:`ShiftingScenario` — a plain
:class:`.events.Scenario` (it runs on every existing runner: the driver
chaos runner, the emulator runner, the batched fleet timeline) plus the
phase metadata the controller certification needs: where the clean phase
ends, which row crashes when (the detection SLO's subject), which rows are
degraded-but-alive (the false-positive sentinel's watch cohort), and when
each certification rumor is injected (the spread SLO's subjects).

Timing convention: every event tick is a multiple of 8, so a fleet
harness stepping 8-tick windows replays the whole family with ONE
compiled window program per knob setting (window lengths never fragment
at event boundaries).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence, Tuple

from .events import (
    AsymmetricLoss,
    Crash,
    FlakyObserver,
    LossStorm,
    Scenario,
    ScenarioError,
)


@dataclass(frozen=True)
class ShiftingScenario:
    """One shifting-conditions cell: the scenario + its SLO subjects.

    ``phases`` is the descriptive (start, end, label) list the artifact
    records; ``rumors`` maps rumor slot -> injection tick (the spread
    SLO measures ticks from injection to full coverage, per slot);
    ``crash_row``/``crash_at`` name the detection SLO's subject (a fleet
    run may vary the row per scenario via ``ops.fleet.FleetVary``);
    ``watch_rows`` is the degraded-but-alive cohort a DEAD verdict about
    which is by construction a false positive."""

    name: str
    scenario: Scenario
    crash_row: int
    crash_at: int
    watch_rows: Tuple[int, ...]
    rumors: Tuple[Tuple[int, int], ...]  # (slot, inject_tick)
    phases: Tuple[Tuple[int, int, str], ...]
    #: ticks after the clean-phase start at which conditions shift (the
    #: controller must react between here and the first false positive)
    shift_at: int

    def __post_init__(self):
        if self.crash_row in self.watch_rows:
            raise ScenarioError(
                "the detection subject cannot also be a false-positive "
                "watch row (a true crash is not a false positive)"
            )


#: the r14/r15 degraded-cohort layout, reused so the measured false-
#: positive physics (ADAPTIVE_BENCH_r14 / FLEET_BENCH_r15) carries over
_ASYM_ROWS = (5, 6, 7)
_FLAKY_ROWS = (9,)
_CRASH_ROW = 20


def _check(n: int, *rows_seqs) -> None:
    for rows in rows_seqs:
        for r in rows:
            if not 0 <= int(r) < n:
                raise ScenarioError(
                    f"shifting scenario needs capacity > {r}; got n={n}"
                )


def loss_storm_midrun(
    n: int = 48,
    clean_ticks: int = 112,
    storm_ticks: int = 120,
    relax_ticks: int = 48,
    storm_pct: float = 20.0,
    asym_pct: float = 70.0,
    crash_at: int = 32,
) -> ShiftingScenario:
    """A LossStorm ARRIVING mid-run: clean phase (a true crash to detect
    fast), then an ambient loss floor plus the r14 loss-adversarial cohort
    (AsymmetricLoss + FlakyObserver — the false-positive adversary), then
    a relax tail (the controller's down-dwell is visible there). One
    certification rumor per phase.

    The ambient floor arrives one beat BEFORE the degraded cohort (at
    ``t1`` vs ``t1 + 8``) and is strong enough (20% → ~0.07 post-rescue
    miss) that a condition-tracking controller has already raised
    protection when the false-positive adversary engages — the margin the
    certification measures. A floor much below ~18% hides under the
    crash-transient band (~1/n) and gives the controller no safe lead."""
    t1 = clean_ticks
    t2 = t1 + storm_ticks
    horizon = t2 + relax_ticks
    _check(n, _ASYM_ROWS, _FLAKY_ROWS, (_CRASH_ROW,))
    scen = Scenario(
        name="loss_storm_midrun",
        events=(
            Crash(rows=[_CRASH_ROW], at=crash_at),
            LossStorm(pct=storm_pct, at=t1, until=t2),
            AsymmetricLoss(rows=list(_ASYM_ROWS), pct=asym_pct,
                           at=t1 + 8, until=t2 - 8, direction="in"),
            FlakyObserver(rows=list(_FLAKY_ROWS), pct=asym_pct,
                          at=t1 + 8, until=t2 - 8),
        ),
        horizon=horizon,
        fp_enforce=False,  # arms are judged by the MC fold, not latching
    )
    return ShiftingScenario(
        name="loss_storm_midrun",
        scenario=scen,
        crash_row=_CRASH_ROW,
        crash_at=crash_at,
        watch_rows=_ASYM_ROWS + _FLAKY_ROWS,
        rumors=((0, 0), (1, t1 + 24)),
        phases=((0, t1, "clean"), (t1, t2, "storm"), (t2, horizon, "relax")),
        shift_at=t1,
    )


def wan_zone_degrade(
    n: int = 48,
    clean_ticks: int = 112,
    degrade_ticks: int = 120,
    relax_ticks: int = 32,
    zone_rows: Sequence[int] = (40, 41, 42, 43, 44, 45, 46, 47),
    pct: float = 55.0,
    crash_at: int = 32,
) -> ShiftingScenario:
    """A WAN zone's links degrading mid-run: every link to AND from the
    ``zone_rows`` cohort (the "remote region" behind one WAN path) gains a
    heavy loss floor — the whole zone looks half-partitioned while staying
    alive, the classic false-positive adversary of a geo deployment. The
    zone members are the watch cohort; the clean-phase crash and the
    per-phase rumors are the detection/spread SLO subjects."""
    t1 = clean_ticks
    t2 = t1 + degrade_ticks
    horizon = t2 + relax_ticks
    zone = tuple(int(r) for r in zone_rows)
    _check(n, zone, (_CRASH_ROW,))
    if _CRASH_ROW in zone:
        raise ScenarioError("crash row must lie outside the WAN zone")
    scen = Scenario(
        name="wan_zone_degrade",
        events=(
            Crash(rows=[_CRASH_ROW], at=crash_at),
            LossStorm(pct=20.0, at=t1, until=t2),
            AsymmetricLoss(rows=list(zone), pct=pct,
                           at=t1 + 8, until=t2 - 8, direction="both"),
        ),
        horizon=horizon,
        fp_enforce=False,
    )
    return ShiftingScenario(
        name="wan_zone_degrade",
        scenario=scen,
        crash_row=_CRASH_ROW,
        crash_at=crash_at,
        watch_rows=zone,
        rumors=((0, 0), (1, t1 + 24)),
        phases=((0, t1, "clean"), (t1, t2, "wan-degraded"),
                (t2, horizon, "relax")),
        shift_at=t1,
    )


def migrating_asym_loss(
    n: int = 48,
    clean_ticks: int = 112,
    phase_ticks: int = 64,
    relax_ticks: int = 32,
    cohort_a: Sequence[int] = (5, 6, 7),
    cohort_b: Sequence[int] = (33, 34, 35),
    pct: float = 70.0,
    crash_at: int = 32,
) -> ShiftingScenario:
    """Asymmetric loss MIGRATING between regions: cohort A degrades first,
    recovers, then cohort B degrades (staggered — the chaos composition
    validator refuses overlapping writes on shared links). The controller
    sees the pressure signal dip between the phases and must NOT relax
    early (the anti-flap dwell's certification case); both cohorts are
    watch rows for the whole run."""
    t1 = clean_ticks
    ta_end = t1 + phase_ticks
    tb_start = ta_end + 8
    tb_end = tb_start + phase_ticks
    horizon = tb_end + relax_ticks
    a = tuple(int(r) for r in cohort_a)
    b = tuple(int(r) for r in cohort_b)
    if set(a) & set(b):
        raise ScenarioError("migrating cohorts must be disjoint")
    _check(n, a, b, (_CRASH_ROW,))
    scen = Scenario(
        name="migrating_asym_loss",
        events=(
            Crash(rows=[_CRASH_ROW], at=crash_at),
            LossStorm(pct=20.0, at=t1, until=tb_end),
            AsymmetricLoss(rows=list(a), pct=pct, at=t1 + 8,
                           until=ta_end, direction="in"),
            AsymmetricLoss(rows=list(b), pct=pct, at=tb_start,
                           until=tb_end - 8, direction="in"),
        ),
        horizon=horizon,
        fp_enforce=False,
    )
    return ShiftingScenario(
        name="migrating_asym_loss",
        scenario=scen,
        crash_row=_CRASH_ROW,
        crash_at=crash_at,
        watch_rows=a + b,
        rumors=((0, 0), (1, t1 + 24)),
        phases=((0, t1, "clean"), (t1, ta_end, "region-A"),
                (tb_start, tb_end, "region-B"), (tb_end, horizon, "relax")),
        shift_at=t1,
    )


#: the default certification family (``control.certify_controller_mc``)
SHIFTING_FAMILY = (
    loss_storm_midrun,
    wan_zone_degrade,
    migrating_asym_loss,
)
