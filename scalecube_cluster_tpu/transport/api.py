"""Transport SPI — the seam every protocol component depends on.

Parity with reference ``Transport`` (transport-api ``Transport.java:11-79``):
the same 4-method contract (``start/stop``, fire-and-forget ``send``,
correlated ``request_response``, hot ``listen()`` stream) plus factory
discovery by config key (``TransportImpl.bind``, ``TransportImpl.java:135-141``
— config -> ServiceLoader -> TCP default; here: config -> registry ->
``memory`` default).

Everything above this boundary (failure detector, gossip, membership,
metadata, facade, testlib scenarios) is transport-agnostic — the invariant
that lets the TPU-simulated mesh (``sim/sim_transport.py``) replace real
sockets without protocol changes.
"""

from __future__ import annotations

import asyncio
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, List

from ..config import TransportConfig
from ..models.message import HEADER_CORRELATION_ID, Message, new_correlation_id
from ..utils.streams import EventStream

MessageHandler = Callable[[Message], Any]

#: Hot fan-out of inbound messages (the ``listen()`` flux analogue).
Listeners = EventStream  # type: ignore[misc]


class TransportError(Exception):
    """Base transport failure (connect/send/decode errors)."""


class PeerUnavailableError(TransportError):
    """Destination address cannot be reached (no such peer / connect refused)."""


from dataclasses import dataclass as _dataclass, field as _field
import time as _time


@_dataclass(frozen=True)
class TransportEvent:
    """Structured transport lifecycle event, emitted on a transport's
    ``transport_events()`` stream (stream transports only today): reconnect
    backoff attempts, the bounded-retry give-up, and outbound connection
    losses. Gives operators/monitors the signal the old "dropping outbound
    connection" log line swallowed.

    kinds: ``reconnect_backoff`` (a retry is scheduled; ``attempts`` so
    far, ``delay`` seconds), ``reconnect_giveup`` (retry budget exhausted —
    the send raised), ``connection_lost`` (an established outbound channel
    died and was evicted from the cache)."""

    kind: str
    address: str
    attempts: int = 0
    delay: float = 0.0
    error: str = ""
    ts: float = _field(default_factory=_time.time)


class Transport(ABC):
    """The 4-method p2p messaging contract (reference Transport.java:11-79)."""

    @property
    @abstractmethod
    def address(self) -> str:
        """Bound listen address of this transport."""

    @abstractmethod
    async def start(self) -> "Transport":
        """Bind and start accepting; returns self (reference ``start()``)."""

    @abstractmethod
    async def stop(self) -> None:
        """Stop accepting, complete the listen stream, release resources."""

    @property
    @abstractmethod
    def is_stopped(self) -> bool: ...

    @abstractmethod
    async def send(self, address: str, message: Message) -> None:
        """Fire-and-forget delivery to ``address`` (at-most-once)."""

    @abstractmethod
    def listen(self) -> Listeners:
        """Hot stream of inbound messages; components filter by qualifier."""

    async def request_response(
        self, address: str, request: Message, timeout: float
    ) -> Message:
        """Correlated RPC: listen-filter-on-cid + send, first match wins
        (reference TransportImpl.java:214-238 — no server-side dispatch
        table; the correlation id in the request must be echoed in the
        response)."""
        cid = request.correlation_id
        if cid is None:
            cid = new_correlation_id()
            request = request.with_header(HEADER_CORRELATION_ID, cid)

        loop = asyncio.get_running_loop()
        fut: "asyncio.Future[Message]" = loop.create_future()

        def on_message(msg: Message) -> None:
            if msg.correlation_id == cid and not fut.done():
                fut.set_result(msg)

        unsubscribe = self.listen().subscribe(on_message)
        try:
            await self.send(address, request)
            return await asyncio.wait_for(fut, timeout)
        finally:
            unsubscribe()


# -- factory registry (ServiceLoader analogue, TransportFactory.java:5) -----

TransportFactoryFn = Callable[[TransportConfig], Transport]
_FACTORIES: Dict[str, TransportFactoryFn] = {}

DEFAULT_FACTORY = "memory"


def register_transport_factory(name: str, factory: TransportFactoryFn) -> None:
    _FACTORIES[name] = factory


def transport_factories() -> List[str]:
    return sorted(_FACTORIES)


def create_transport(config: TransportConfig) -> Transport:
    """Resolve factory from config (reference TransportImpl.bind:135-141)."""
    name = config.transport_factory or DEFAULT_FACTORY
    try:
        factory = _FACTORIES[name]
    except KeyError:
        raise TransportError(
            f"unknown transport factory {name!r}; registered: {transport_factories()}"
        ) from None
    return factory(config)


async def bind_transport(config: TransportConfig) -> Transport:
    """Create + start in one call (reference ``Transport.bind`` convenience)."""
    return await create_transport(config).start()
