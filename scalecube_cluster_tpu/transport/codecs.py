"""Pluggable wire codecs.

Parity with the reference codec layer: ``MessageCodec``
(``MessageCodec.java:8-27``, stream-based message serialization applied at the
channel boundary, ``TransportImpl.java:240-260``) and ``MetadataCodec``
(ByteBuffer-based); implementations are discovered via a registry (the
``META-INF/services`` ServiceLoader analogue). The reference ships JDK
serialization (default), Jackson-JSON and Jackson-Smile; here:

* ``jdk``  -> pickle (the platform-native object serialization, default);
* ``json`` -> UTF-8 JSON (cross-language, payload must be JSON-encodable);
* ``smile`` is a binary-JSON variant in the reference; our binary alternative
  is the pickle codec, so ``smile`` aliases ``jdk``.
"""

from __future__ import annotations

import json
import pickle
from abc import ABC, abstractmethod
from typing import Any, Dict

from ..models.message import Message


class MessageCodec(ABC):
    """Message <-> bytes (reference MessageCodec.java:8-27)."""

    @abstractmethod
    def encode(self, message: Message) -> bytes: ...

    @abstractmethod
    def decode(self, payload: bytes) -> Message: ...


class PickleMessageCodec(MessageCodec):
    """Platform-native serialization (reference JdkMessageCodec.java:9)."""

    def encode(self, message: Message) -> bytes:
        return pickle.dumps((message.headers, message.data), protocol=pickle.HIGHEST_PROTOCOL)

    def decode(self, payload: bytes) -> Message:
        headers, data = pickle.loads(payload)
        return Message(headers=headers, data=data)


class JsonMessageCodec(MessageCodec):
    """Cross-language JSON codec (reference JacksonMessageCodec.java:9)."""

    def encode(self, message: Message) -> bytes:
        return json.dumps({"headers": message.headers, "data": message.data}).encode("utf-8")

    def decode(self, payload: bytes) -> Message:
        obj = json.loads(payload.decode("utf-8"))
        return Message(headers=obj.get("headers", {}), data=obj.get("data"))


class MetadataCodec(ABC):
    """Metadata object <-> bytes (reference MetadataCodec interface)."""

    @abstractmethod
    def serialize(self, metadata: Any) -> bytes: ...

    @abstractmethod
    def deserialize(self, payload: bytes) -> Any: ...


class PickleMetadataCodec(MetadataCodec):
    def serialize(self, metadata: Any) -> bytes:
        return pickle.dumps(metadata, protocol=pickle.HIGHEST_PROTOCOL)

    def deserialize(self, payload: bytes) -> Any:
        return pickle.loads(payload)


class JsonMetadataCodec(MetadataCodec):
    def serialize(self, metadata: Any) -> bytes:
        return json.dumps(metadata).encode("utf-8")

    def deserialize(self, payload: bytes) -> Any:
        return json.loads(payload.decode("utf-8"))


_MESSAGE_CODECS: Dict[str, MessageCodec] = {}
_METADATA_CODECS: Dict[str, MetadataCodec] = {}


def register_message_codec(name: str, codec: MessageCodec) -> None:
    _MESSAGE_CODECS[name] = codec


def register_metadata_codec(name: str, codec: MetadataCodec) -> None:
    _METADATA_CODECS[name] = codec


def message_codec(name: str) -> MessageCodec:
    try:
        return _MESSAGE_CODECS[name]
    except KeyError:
        raise ValueError(f"unknown message codec {name!r}; registered: {sorted(_MESSAGE_CODECS)}") from None


def metadata_codec(name: str) -> MetadataCodec:
    try:
        return _METADATA_CODECS[name]
    except KeyError:
        raise ValueError(f"unknown metadata codec {name!r}; registered: {sorted(_METADATA_CODECS)}") from None


register_message_codec("jdk", PickleMessageCodec())
register_message_codec("smile", PickleMessageCodec())
register_message_codec("json", JsonMessageCodec())
register_metadata_codec("jdk", PickleMetadataCodec())
register_metadata_codec("json", JsonMetadataCodec())
