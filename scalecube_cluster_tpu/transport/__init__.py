from . import codecs, local, native_codec, tcp, websocket  # register factories/codecs (ServiceLoader analogue)
from .api import (
    Listeners,
    PeerUnavailableError,
    Transport,
    TransportError,
    TransportEvent,
    bind_transport,
    create_transport,
    register_transport_factory,
    transport_factories,
)
from .emulator import (
    NetworkEmulator,
    NetworkEmulatorError,
    NetworkEmulatorTransport,
)
from .local import MemoryTransport, MemoryTransportRegistry
from .tcp import TcpTransport
from .websocket import WebsocketTransport

__all__ = [
    "Transport",
    "TransportError",
    "TransportEvent",
    "PeerUnavailableError",
    "Listeners",
    "bind_transport",
    "create_transport",
    "register_transport_factory",
    "transport_factories",
    "NetworkEmulator",
    "NetworkEmulatorError",
    "NetworkEmulatorTransport",
    "MemoryTransport",
    "MemoryTransportRegistry",
    "TcpTransport",
    "WebsocketTransport",
    "codecs",
]
