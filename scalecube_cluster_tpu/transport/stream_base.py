"""Shared scaffolding for stream-socket transports (TCP, WebSocket).

Both real wire protocols share everything except framing and handshake: a
listening asyncio server, a lazily-connected cached client connection per
peer (the reference's connection cache, ``TransportImpl.java:54`` /
``connect0:262-278``), codec-pluggable serialization at the channel
boundary, and teardown that also reaps connections still mid-establishment
when ``stop()`` runs. Subclasses supply the scheme, the client-side
connection setup (handshake), the outbound frame encoding, and the inbound
read loop.
"""

from __future__ import annotations

import asyncio
import logging
from abc import abstractmethod
from typing import Dict, Optional, Tuple

import random

from ..config import TransportConfig
from ..models.message import Message
from .api import (
    Listeners,
    PeerUnavailableError,
    Transport,
    TransportError,
    TransportEvent,
)
from .codecs import message_codec

logger = logging.getLogger(__name__)


def parse_host_port(address: str, scheme: str) -> Tuple[str, int]:
    addr = address[len(scheme):] if address.startswith(scheme) else address
    host, _, port = addr.rpartition(":")
    if not host or not port.isdigit():
        raise TransportError(f"bad {scheme} address: {address!r}")
    return host, int(port)


class CachedConnection:
    """One cached outbound connection with FIFO write ordering and an
    optional background reader task (protocols that must service inbound
    control frames on the outbound channel, e.g. WebSocket PING)."""

    def __init__(self, writer: asyncio.StreamWriter):
        self.writer = writer
        self.lock = asyncio.Lock()
        self.reader_task: Optional[asyncio.Task] = None

    async def write_bytes(self, data: bytes) -> None:
        async with self.lock:
            self.writer.write(data)
            await self.writer.drain()

    def close(self) -> None:
        if self.reader_task is not None:
            self.reader_task.cancel()
        try:
            self.writer.close()
        except Exception:  # noqa: BLE001
            pass


def _close_when_done(fut: "asyncio.Future[CachedConnection]") -> None:
    if not fut.cancelled() and fut.exception() is None:
        fut.result().close()


class StreamTransportBase(Transport):
    """Server + cached-lazy-client plumbing shared by TCP and WebSocket."""

    scheme: str = ""

    def __init__(self, config: TransportConfig):
        self._config = config
        self._codec = message_codec(config.message_codec)
        self._listeners = Listeners()
        self._server: Optional[asyncio.base_events.Server] = None
        self._address: Optional[str] = None
        self._stopped = False
        # peer address -> pending/established connection (TransportImpl.java:54)
        self._connections: Dict[str, "asyncio.Future[CachedConnection]"] = {}
        self._inbound_writers: set = set()
        # transport lifecycle events (reconnect backoff/giveup, connection
        # loss) — see api.TransportEvent; lazily consumed, never required
        self._events = Listeners()

    # -- subclass hooks ------------------------------------------------------
    @abstractmethod
    async def _setup_inbound(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        """Server-side channel setup (e.g. websocket upgrade); no-op for raw."""

    @abstractmethod
    async def _read_payload(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> Optional[bytes]:
        """Read one whole encoded message; None when the peer closed cleanly."""

    @abstractmethod
    async def _setup_outbound(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        host: str,
        port: int,
    ) -> None:
        """Client-side channel setup (e.g. websocket handshake)."""

    @abstractmethod
    def _frame(self, payload: bytes) -> bytes:
        """Wrap one encoded message for the wire (length prefix / ws frame)."""

    def _start_outbound_reader(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        conn: "CachedConnection",
        address: str,
    ) -> None:
        """Hook: service the outbound channel's inbound half (control frames
        / peer replies). Default: nothing to read on a raw stream."""

    # -- Transport contract --------------------------------------------------
    @property
    def address(self) -> str:
        if self._address is None:
            raise TransportError("transport not started")
        return self._address

    @property
    def is_stopped(self) -> bool:
        return self._stopped

    async def start(self) -> "StreamTransportBase":
        host, port = self._config.host, self._config.port
        self._server = await asyncio.start_server(self._accept, host=host, port=port)
        bound = self._server.sockets[0].getsockname()
        self._address = f"{self.scheme}{host}:{bound[1]}"
        return self

    async def _accept(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._inbound_writers.add(writer)
        try:
            await self._setup_inbound(reader, writer)
            while not self._stopped:
                payload = await self._read_payload(reader, writer)
                if payload is None:
                    break
                self._listeners.emit(self._codec.decode(payload))
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass  # peer went away: normal churn
        except TransportError as exc:
            # wire-protocol violation (oversized frame, bad upgrade, broken
            # fragmentation): tear the channel down, but leave a trace — a
            # silent close makes version-skewed peers undebuggable
            logger.warning("[%s] dropping inbound connection: %s", self._address, exc)
        finally:
            self._inbound_writers.discard(writer)
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        for fut in self._connections.values():
            if fut.done():
                _close_when_done(fut)
            else:
                # a connect in flight when stop() runs must not leak its
                # socket once it completes
                fut.add_done_callback(_close_when_done)
        self._connections.clear()
        # Abort accepted connections so their handler coroutines finish —
        # Server.wait_closed() (py3.12+) blocks until all handlers complete.
        for writer in list(self._inbound_writers):
            try:
                writer.transport.abort()
            except Exception:  # noqa: BLE001
                pass
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _connect(self, address: str) -> CachedConnection:
        """Lazy cached connect (reference connect0, TransportImpl.java:262-278)."""
        fut = self._connections.get(address)
        if fut is not None:
            if not fut.done() or fut.exception() is None:
                return await asyncio.shield(fut)
            del self._connections[address]  # retry after failed connect
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._connections[address] = fut
        try:
            host, port = parse_host_port(address, self.scheme)
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, port), self._config.connect_timeout
            )
            await asyncio.wait_for(
                self._setup_outbound(reader, writer, host, port),
                self._config.connect_timeout,
            )
            conn = CachedConnection(writer)
            self._start_outbound_reader(reader, writer, conn, address)
            fut.set_result(conn)
            return conn
        except Exception as exc:  # noqa: BLE001
            err = PeerUnavailableError(f"connect to {address} failed: {exc}")
            fut.set_exception(err)
            # consume so the loop doesn't warn about unretrieved exceptions
            fut.exception()
            self._connections.pop(address, None)
            raise err from exc

    def _emit_event(self, kind: str, address: str, attempts: int = 0,
                    delay: float = 0.0, error: str = "") -> None:
        self._events.emit(TransportEvent(
            kind=kind, address=address, attempts=attempts, delay=delay,
            error=error,
        ))

    def _backoff_delay(self, attempt: int) -> float:
        """Exponential backoff with +-50% jitter, capped: attempt 1 waits
        ~base, attempt 2 ~2*base, ... A synchronized retry stampede against
        a rebooting peer is exactly what the jitter breaks up."""
        base = self._config.reconnect_base_delay * (2 ** (attempt - 1))
        return min(base, self._config.reconnect_max_delay) * (
            0.5 + random.random()
        )

    async def send(self, address: str, message: Message) -> None:
        """Fire-and-forget send over the cached connection, with BOUNDED
        reconnect: a failed connect or a connection that dies mid-send is
        retried up to ``config.reconnect_max_retries`` extra times with
        exponential backoff + jitter (the pre-r7 behavior silently dropped
        the cached connection and failed the send). Exhausting the budget
        raises ``PeerUnavailableError`` AND emits a ``reconnect_giveup``
        transport event — churn monitoring must be able to see give-ups
        without scraping logs. Retrying a write that may have partially
        left the socket keeps at-most-once per ATTEMPT, like the
        reference's reconnect-then-resend; SWIM tolerates duplicates by
        design (every merge is idempotent)."""
        if self._stopped:
            raise TransportError("transport is stopped")
        payload = self._codec.encode(message)
        if len(payload) > self._config.max_frame_length:
            raise TransportError(f"frame too large: {len(payload)}")
        attempt = 0
        while True:
            try:
                conn = await self._connect(address)
                await conn.write_bytes(self._frame(payload))
                return
            except (PeerUnavailableError, ConnectionResetError,
                    BrokenPipeError) as exc:
                self._connections.pop(address, None)
                attempt += 1
                if self._stopped or attempt > self._config.reconnect_max_retries:
                    self._emit_event(
                        "reconnect_giveup", address, attempts=attempt,
                        error=str(exc),
                    )
                    raise PeerUnavailableError(
                        f"send to {address} failed after {attempt} "
                        f"attempt(s): {exc}"
                    ) from exc
                delay = self._backoff_delay(attempt)
                self._emit_event(
                    "reconnect_backoff", address, attempts=attempt,
                    delay=delay, error=str(exc),
                )
                await asyncio.sleep(delay)

    def listen(self) -> Listeners:
        return self._listeners

    def transport_events(self) -> Listeners:
        """Hot stream of :class:`..transport.api.TransportEvent` (reconnect
        backoff / give-up, outbound connection loss)."""
        return self._events
