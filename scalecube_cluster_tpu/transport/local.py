"""In-process loopback transport.

The testlib workhorse (role of the reference's loopback TCP in single-JVM
tests): every ``MemoryTransport`` registers in a process-wide address table;
``send`` enqueues onto the destination's listen stream via the event loop,
preserving per-sender FIFO order (the reference's in-order channel guarantee,
``TcpTransportSendOrderTest``).

Addresses look like ``mem://<n>`` and are allocated sequentially; a fixed
"port" can be requested for restart-on-same-address scenarios
(reference ClusterTest start/stop on fixed port).
"""

from __future__ import annotations

import asyncio
import itertools
from typing import Dict, Optional

from ..config import TransportConfig
from ..models.message import Message
from .api import (
    Listeners,
    PeerUnavailableError,
    Transport,
    TransportError,
    register_transport_factory,
)

_SCHEME = "mem://"


class MemoryTransportRegistry:
    """Process-wide address -> transport table (one per test/world if desired)."""

    _default: Optional["MemoryTransportRegistry"] = None

    def __init__(self) -> None:
        self._table: Dict[str, "MemoryTransport"] = {}
        self._ports = itertools.count(1)

    @classmethod
    def default(cls) -> "MemoryTransportRegistry":
        if cls._default is None:
            cls._default = MemoryTransportRegistry()
        return cls._default

    @classmethod
    def reset_default(cls) -> None:
        cls._default = None

    def allocate_address(self, port: int) -> str:
        if port == 0:
            port = next(self._ports)
        addr = f"{_SCHEME}{port}"
        if addr in self._table:
            raise TransportError(f"address already bound: {addr}")
        return addr

    def bind(self, addr: str, transport: "MemoryTransport") -> None:
        self._table[addr] = transport

    def unbind(self, addr: str) -> None:
        self._table.pop(addr, None)

    def lookup(self, addr: str) -> Optional["MemoryTransport"]:
        return self._table.get(addr)


class MemoryTransport(Transport):
    """Loopback transport over an in-process registry."""

    def __init__(self, config: TransportConfig, registry: Optional[MemoryTransportRegistry] = None):
        self._config = config
        self._registry = registry or MemoryTransportRegistry.default()
        self._address: Optional[str] = None
        self._listeners = Listeners()
        self._stopped = False

    @property
    def address(self) -> str:
        if self._address is None:
            raise TransportError("transport not started")
        return self._address

    @property
    def is_stopped(self) -> bool:
        return self._stopped

    async def start(self) -> "MemoryTransport":
        self._address = self._registry.allocate_address(self._config.port)
        self._registry.bind(self._address, self)
        return self

    async def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        if self._address is not None:
            self._registry.unbind(self._address)

    async def send(self, address: str, message: Message) -> None:
        if self._stopped:
            raise TransportError("transport is stopped")
        peer = self._registry.lookup(address)
        if peer is None or peer.is_stopped:
            raise PeerUnavailableError(f"no transport bound at {address}")
        # call_soon keeps per-sender FIFO order and breaks reentrancy, the
        # analogue of the reference's channel write -> remote event loop hop.
        asyncio.get_running_loop().call_soon(peer._deliver, message)

    def _deliver(self, message: Message) -> None:
        if not self._stopped:
            self._listeners.emit(message)

    def listen(self) -> Listeners:
        return self._listeners


def _memory_factory(config: TransportConfig) -> MemoryTransport:
    return MemoryTransport(config)


register_transport_factory("memory", _memory_factory)
