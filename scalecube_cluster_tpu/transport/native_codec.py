"""Binary message codec: native C fast path + pure-Python fallback.

Registered under the codec key ``"binary"``. Unlike the pickle codec, the
wire format is language-neutral (header map + payload, fixed-width
big-endian lengths — see ``native/codec.c`` for the layout), so non-Python
peers can speak it; the payload itself is raw bytes when ``Message.data``
is bytes/str, pickled otherwise (flagged in a reserved header).

The C extension is compiled on first use with the system compiler; if that
fails, :class:`_PyWire` implements the byte-identical format in struct
calls, so the codec works everywhere and the two paths interoperate.
"""

from __future__ import annotations

import pickle
import struct
from typing import Dict, Tuple

from ..models.message import Message
from .codecs import MessageCodec, register_message_codec

_DATA_KIND = "-bin-kind"  # reserved header: payload interpretation
_U16 = struct.Struct(">H")
_U32 = struct.Struct(">I")


class _PyWire:
    """Pure-Python implementation of the native wire format."""

    @staticmethod
    def encode(headers: Dict[str, str], payload: bytes) -> bytes:
        parts = [b"S1", _U16.pack(len(headers))]
        for k, v in headers.items():
            kb, vb = k.encode(), v.encode()
            parts += [_U16.pack(len(kb)), kb, _U32.pack(len(vb)), vb]
        parts += [_U32.pack(len(payload)), payload]
        return b"".join(parts)

    @staticmethod
    def decode(buf: bytes) -> Tuple[Dict[str, str], bytes]:
        if len(buf) < 8 or buf[:2] != b"S1":
            raise ValueError("bad magic")
        (hcount,) = _U16.unpack_from(buf, 2)
        offset = 4
        headers: Dict[str, str] = {}
        try:
            for _ in range(hcount):
                (klen,) = _U16.unpack_from(buf, offset)
                offset += 2
                k = buf[offset : offset + klen].decode()
                offset += klen
                (vlen,) = _U32.unpack_from(buf, offset)
                offset += 4
                headers[k] = buf[offset : offset + vlen].decode()
                offset += vlen
            (plen,) = _U32.unpack_from(buf, offset)
            offset += 4
            payload = buf[offset : offset + plen]
            if len(payload) != plen:
                raise ValueError("truncated frame")
        except struct.error as e:
            raise ValueError("truncated frame") from e
        return headers, payload


def _load_wire():
    from ..native import load_codec

    return load_codec() or _PyWire


class BinaryMessageCodec(MessageCodec):
    """Message <-> native wire format (C extension when buildable).

    The wire backend resolves lazily on first use, so importing the
    transport package never shells out to a compiler; a failed build is
    cached (in native.load_codec) and falls back to the Python format."""

    def __init__(self, wire=None):
        self._wire_override = wire

    @property
    def _wire(self):
        if self._wire_override is None:
            self._wire_override = _load_wire()
        return self._wire_override

    @property
    def is_native(self) -> bool:
        return self._wire is not _PyWire

    def encode(self, message: Message) -> bytes:
        headers = dict(message.headers)
        data = message.data
        if data is None:
            kind, payload = "none", b""
        elif isinstance(data, bytes):
            kind, payload = "bytes", data
        elif isinstance(data, str):
            kind, payload = "str", data.encode()
        else:
            kind, payload = "pickle", pickle.dumps(data)
        headers[_DATA_KIND] = kind
        return self._wire.encode(headers, payload)

    def decode(self, payload: bytes) -> Message:
        headers, body = self._wire.decode(payload)
        kind = headers.pop(_DATA_KIND, "bytes")
        if kind == "none":
            data = None
        elif kind == "str":
            data = body.decode()
        elif kind == "pickle":
            data = pickle.loads(body)
        else:
            data = body
        return Message(data=data, headers=headers)


register_message_codec("binary", BinaryMessageCodec())
