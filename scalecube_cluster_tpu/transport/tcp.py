"""Real TCP transport over asyncio streams.

Parity with the reference Netty TCP transport (``TransportImpl.java``,
``TcpReceiver.java:22-49``, ``TcpSender.java:24-57``): a listening server, a
lazily-connected cached client connection per peer
(``TransportImpl.connect0``, ``TransportImpl.java:262-278``), 4-byte
big-endian length-prefixed framing (``TcpChannelInitializer.java:28-33``) with
a max-frame guard, and codec-pluggable message serialization at the channel
boundary (``TransportImpl.java:240-260``). Server/client/cache scaffolding
lives in :mod:`.stream_base`, shared with the WebSocket transport.

This is the DCN-facing path for genuine multi-process clusters; addresses are
``tcp://host:port``.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Optional, Tuple

from ..config import TransportConfig
from .api import TransportError, register_transport_factory
from .stream_base import StreamTransportBase, parse_host_port

_SCHEME = "tcp://"
_LEN = struct.Struct(">I")


def parse_tcp_address(address: str) -> Tuple[str, int]:
    return parse_host_port(address, _SCHEME)


class TcpTransport(StreamTransportBase):
    """Length-prefixed TCP transport with cached lazy connections."""

    scheme = _SCHEME

    def __init__(self, config: TransportConfig):
        super().__init__(config)

    async def _setup_inbound(self, reader, writer) -> None:
        pass  # raw stream: no handshake

    async def _setup_outbound(self, reader, writer, host, port) -> None:
        pass

    def _frame(self, payload: bytes) -> bytes:
        return _LEN.pack(len(payload)) + payload

    async def _read_payload(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> Optional[bytes]:
        header = await reader.readexactly(_LEN.size)
        (length,) = _LEN.unpack(header)
        if length > self._config.max_frame_length:
            raise TransportError(f"frame too large: {length}")
        return await reader.readexactly(length)


register_transport_factory("tcp", lambda cfg: TcpTransport(cfg))
