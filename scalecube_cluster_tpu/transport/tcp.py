"""Real TCP transport over asyncio streams.

Parity with the reference Netty TCP transport (``TransportImpl.java``,
``TcpReceiver.java:22-49``, ``TcpSender.java:24-57``): a listening server, a
lazily-connected cached client connection per peer
(``TransportImpl.connect0``, ``TransportImpl.java:262-278``), 4-byte
big-endian length-prefixed framing (``TcpChannelInitializer.java:28-33``) with
a max-frame guard, and codec-pluggable message serialization at the channel
boundary (``TransportImpl.java:240-260``).

This is the DCN-facing path for genuine multi-process clusters; addresses are
``tcp://host:port``.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Dict, Optional, Tuple

from ..config import TransportConfig
from ..models.message import Message
from .api import (
    Listeners,
    PeerUnavailableError,
    Transport,
    TransportError,
    register_transport_factory,
)
from .codecs import message_codec

_SCHEME = "tcp://"
_LEN = struct.Struct(">I")


def parse_tcp_address(address: str) -> Tuple[str, int]:
    addr = address[len(_SCHEME):] if address.startswith(_SCHEME) else address
    host, _, port = addr.rpartition(":")
    if not host or not port.isdigit():
        raise TransportError(f"bad tcp address: {address!r}")
    return host, int(port)


class _Connection:
    """One cached outbound connection with FIFO write ordering."""

    def __init__(self, writer: asyncio.StreamWriter):
        self.writer = writer
        self.lock = asyncio.Lock()

    async def send_frame(self, frame: bytes) -> None:
        async with self.lock:
            self.writer.write(_LEN.pack(len(frame)) + frame)
            await self.writer.drain()

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:  # noqa: BLE001
            pass


class TcpTransport(Transport):
    """Length-prefixed TCP transport with cached lazy connections."""

    def __init__(self, config: TransportConfig):
        self._config = config
        self._codec = message_codec(config.message_codec)
        self._listeners = Listeners()
        self._server: Optional[asyncio.base_events.Server] = None
        self._address: Optional[str] = None
        self._stopped = False
        # peer address -> pending/established connection (TransportImpl.java:54)
        self._connections: Dict[str, "asyncio.Future[_Connection]"] = {}
        self._inbound_writers: set = set()

    @property
    def address(self) -> str:
        if self._address is None:
            raise TransportError("transport not started")
        return self._address

    @property
    def is_stopped(self) -> bool:
        return self._stopped

    async def start(self) -> "TcpTransport":
        host, port = self._config.host, self._config.port
        self._server = await asyncio.start_server(self._accept, host=host, port=port)
        bound = self._server.sockets[0].getsockname()
        self._address = f"{_SCHEME}{host}:{bound[1]}"
        return self

    async def _accept(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._inbound_writers.add(writer)
        try:
            while not self._stopped:
                header = await reader.readexactly(_LEN.size)
                (length,) = _LEN.unpack(header)
                if length > self._config.max_frame_length:
                    raise TransportError(f"frame too large: {length}")
                frame = await reader.readexactly(length)
                self._listeners.emit(self._codec.decode(frame))
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            self._inbound_writers.discard(writer)
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        for fut in self._connections.values():
            if fut.done() and not fut.cancelled() and fut.exception() is None:
                fut.result().close()
        self._connections.clear()
        # Abort accepted connections so their handler coroutines finish —
        # Server.wait_closed() (py3.12+) blocks until all handlers complete.
        for writer in list(self._inbound_writers):
            try:
                writer.transport.abort()
            except Exception:  # noqa: BLE001
                pass
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _connect(self, address: str) -> _Connection:
        """Lazy cached connect (reference connect0, TransportImpl.java:262-278)."""
        fut = self._connections.get(address)
        if fut is not None:
            if not fut.done() or fut.exception() is None:
                return await asyncio.shield(fut)
            del self._connections[address]  # retry after failed connect
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._connections[address] = fut
        try:
            host, port = parse_tcp_address(address)
            _, writer = await asyncio.wait_for(
                asyncio.open_connection(host, port), self._config.connect_timeout
            )
            conn = _Connection(writer)
            fut.set_result(conn)
            return conn
        except Exception as exc:  # noqa: BLE001
            err = PeerUnavailableError(f"connect to {address} failed: {exc}")
            fut.set_exception(err)
            # consume so the loop doesn't warn about unretrieved exceptions
            fut.exception()
            self._connections.pop(address, None)
            raise err from exc

    async def send(self, address: str, message: Message) -> None:
        if self._stopped:
            raise TransportError("transport is stopped")
        conn = await self._connect(address)
        frame = self._codec.encode(message)
        if len(frame) > self._config.max_frame_length:
            raise TransportError(f"frame too large: {len(frame)}")
        try:
            await conn.send_frame(frame)
        except (ConnectionResetError, BrokenPipeError) as exc:
            self._connections.pop(address, None)
            raise PeerUnavailableError(f"send to {address} failed: {exc}") from exc

    def listen(self) -> Listeners:
        return self._listeners


register_transport_factory("tcp", lambda cfg: TcpTransport(cfg))
