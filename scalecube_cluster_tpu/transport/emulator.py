"""Network fault injection at the transport seam.

Parity with reference ``NetworkEmulator`` (cluster-testlib
``NetworkEmulator.java:26-417``): per-destination outbound settings (loss
percent + exponentially-distributed delay with given mean), per-source inbound
pass/block flag, defaults for unconfigured links, block/unblock for one or
all peers, and sent/lost counters — plus the ``NetworkEmulatorTransport``
decorator (``NetworkEmulatorTransport.java:9-89``) that applies outbound
fail -> delay before send and filters inbound on the listen stream.

The vectorized sim applies the same model on-device: loss/delay become
Bernoulli/exponential draws against an N×N link matrix inside the tick kernel
(``ops/kernel.py`` — the FD and gossip phases); this module is the scalar-engine and
real-transport version, and the oracle for those kernel draws.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass
from typing import Dict, Iterable, Optional

from ..models.message import HEADER_SENDER, Message
from .api import Listeners, Transport, TransportError


class NetworkEmulatorError(TransportError):
    """Raised when the emulator drops an outbound message."""


@dataclass(frozen=True)
class OutboundSettings:
    """Loss %% and mean delay (seconds) for one directed link
    (reference NetworkEmulator.OutboundSettings:310-386)."""

    loss_percent: float = 0.0
    mean_delay: float = 0.0

    def evaluate_loss(self, rng: random.Random) -> bool:
        """True if the message should be dropped."""
        return self.loss_percent > 0 and (
            self.loss_percent >= 100 or rng.uniform(0, 100) < self.loss_percent
        )

    def evaluate_delay(self, rng: random.Random) -> float:
        """Exponential delay sample with the configured mean
        (reference NetworkEmulator.java:349-369)."""
        if self.mean_delay <= 0:
            return 0.0
        return rng.expovariate(1.0 / self.mean_delay)


@dataclass(frozen=True)
class InboundSettings:
    """Pass/block flag for one inbound peer (reference InboundSettings:388-417)."""

    shall_pass: bool = True


class NetworkEmulator:
    """Mutable per-link fault model, safe to reconfigure while running."""

    def __init__(self, address: str = "", seed: Optional[int] = None) -> None:
        self._address = address
        self._rng = random.Random(seed)
        self._outbound: Dict[str, OutboundSettings] = {}
        self._inbound: Dict[str, InboundSettings] = {}
        self._default_outbound = OutboundSettings()
        self._default_inbound = InboundSettings()
        self.total_message_sent_count = 0
        self.total_message_lost_count = 0

    # -- outbound ----------------------------------------------------------
    def outbound_settings(self, destination: str) -> OutboundSettings:
        return self._outbound.get(destination, self._default_outbound)

    def set_outbound_settings(
        self, destination: str, loss_percent: float, mean_delay: float = 0.0
    ) -> None:
        self._outbound[destination] = OutboundSettings(loss_percent, mean_delay)

    def set_default_outbound_settings(self, loss_percent: float, mean_delay: float = 0.0) -> None:
        self._default_outbound = OutboundSettings(loss_percent, mean_delay)

    def block_outbound(self, destinations: Iterable[str]) -> None:
        for d in destinations:
            self._outbound[d] = OutboundSettings(100.0, 0.0)

    def unblock_outbound(self, destinations: Iterable[str]) -> None:
        for d in destinations:
            self._outbound.pop(d, None)

    def block_all_outbound(self) -> None:
        self._outbound.clear()
        self._default_outbound = OutboundSettings(100.0, 0.0)

    def unblock_all_outbound(self) -> None:
        self._outbound.clear()
        self._default_outbound = OutboundSettings()

    async def try_fail_and_delay(self, destination: str) -> None:
        """Apply loss then delay for one outbound message; raises on drop
        (reference NetworkEmulatorTransport outbound pipeline :50-75)."""
        settings = self.outbound_settings(destination)
        self.total_message_sent_count += 1
        if settings.evaluate_loss(self._rng):
            self.total_message_lost_count += 1
            raise NetworkEmulatorError(f"emulator dropped message {self._address} -> {destination}")
        delay = settings.evaluate_delay(self._rng)
        if delay > 0:
            await asyncio.sleep(delay)

    # -- inbound -----------------------------------------------------------
    def inbound_settings(self, source: str) -> InboundSettings:
        return self._inbound.get(source, self._default_inbound)

    def set_inbound_settings(self, source: str, shall_pass: bool) -> None:
        self._inbound[source] = InboundSettings(shall_pass)

    def set_default_inbound_settings(self, shall_pass: bool) -> None:
        self._default_inbound = InboundSettings(shall_pass)

    def block_inbound(self, sources: Iterable[str]) -> None:
        for s in sources:
            self._inbound[s] = InboundSettings(False)

    def unblock_inbound(self, sources: Iterable[str]) -> None:
        for s in sources:
            self._inbound.pop(s, None)

    def block_all_inbound(self) -> None:
        self._inbound.clear()
        self._default_inbound = InboundSettings(False)

    def unblock_all_inbound(self) -> None:
        self._inbound.clear()
        self._default_inbound = InboundSettings(True)


class NetworkEmulatorTransport(Transport):
    """Decorator applying the emulator around any transport
    (reference NetworkEmulatorTransport.java:9-89); also stamps the sender
    header on outbound messages (:85-87)."""

    def __init__(self, delegate: Transport, emulator: Optional[NetworkEmulator] = None):
        self._delegate = delegate
        self._emulator = emulator or NetworkEmulator()
        self._listeners = Listeners()
        self._wired = False

    @property
    def network_emulator(self) -> NetworkEmulator:
        return self._emulator

    @property
    def address(self) -> str:
        return self._delegate.address

    @property
    def is_stopped(self) -> bool:
        return self._delegate.is_stopped

    async def start(self) -> "NetworkEmulatorTransport":
        await self._delegate.start()
        self._wire()
        return self

    def _wire(self) -> None:
        if not self._wired:
            self._emulator._address = self._delegate.address
            self._delegate.listen().subscribe(self._on_inbound)
            self._wired = True

    def _on_inbound(self, message: Message) -> None:
        sender = message.sender
        if sender is not None and not self._emulator.inbound_settings(sender).shall_pass:
            return
        self._listeners.emit(message)

    async def stop(self) -> None:
        await self._delegate.stop()

    async def send(self, address: str, message: Message) -> None:
        message = message.with_header(HEADER_SENDER, self.address)
        await self._emulator.try_fail_and_delay(address)
        await self._delegate.send(address, message)

    def listen(self) -> Listeners:
        self._wire()
        return self._listeners
