"""WebSocket transport — the second real wire protocol behind the SPI.

Parity with the reference's WebSocket transport (binary frames over an HTTP
upgrade: ``WebsocketTransportFactory.java:8``, ``WebsocketReceiver.java:52``,
``WebsocketSender.java:41``): one encoded message per binary frame (the
frame layer replaces TCP's explicit length prefix), lazily-connected cached
client connection per peer, codec-pluggable serialization at the channel
boundary. Server/client/cache scaffolding lives in :mod:`.stream_base`,
shared with the TCP transport. Addresses are ``ws://host:port``.

Self-contained RFC 6455 implementation over asyncio streams (no external
dependency): HTTP/1.1 upgrade handshake with ``Sec-WebSocket-Accept``
validation, client-to-server frame masking as the RFC requires, 7/16/64-bit
payload lengths, PING→PONG replies, CLOSE handling. Fragmented messages
(continuation frames) are reassembled under the max-frame cap; a data frame
arriving mid-fragmentation fails the connection (RFC 6455 §5.4).
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import logging
import os
import struct
from typing import Optional, Tuple

from ..config import TransportConfig
from .api import TransportError, register_transport_factory
from .stream_base import StreamTransportBase, parse_host_port

logger = logging.getLogger(__name__)

_SCHEME = "ws://"
_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"  # RFC 6455 §1.3

_OP_CONT = 0x0
_OP_BINARY = 0x2
_OP_CLOSE = 0x8
_OP_PING = 0x9
_OP_PONG = 0xA


def parse_ws_address(address: str) -> Tuple[str, int]:
    return parse_host_port(address, _SCHEME)


def _accept_key(client_key: str) -> str:
    digest = hashlib.sha1((client_key + _WS_GUID).encode("ascii")).digest()
    return base64.b64encode(digest).decode("ascii")


def _mask_payload(mask: bytes, payload: bytes) -> bytes:
    # XOR with the repeating 4-byte mask — int-wide XOR beats a byte loop
    reps = (len(payload) + 3) // 4
    key = int.from_bytes(mask * reps, "little")
    data = int.from_bytes(payload.ljust(reps * 4, b"\0"), "little")
    return (data ^ key).to_bytes(reps * 4, "little")[: len(payload)]


def _encode_frame(opcode: int, payload: bytes, mask: bool) -> bytes:
    head = bytes([0x80 | opcode])  # FIN + opcode
    mask_bit = 0x80 if mask else 0
    n = len(payload)
    if n < 126:
        head += bytes([mask_bit | n])
    elif n < (1 << 16):
        head += bytes([mask_bit | 126]) + struct.pack(">H", n)
    else:
        head += bytes([mask_bit | 127]) + struct.pack(">Q", n)
    if mask:
        key = os.urandom(4)
        return head + key + _mask_payload(key, payload)
    return head + payload


async def _read_frame(reader: asyncio.StreamReader, max_len: int) -> Tuple[int, bool, bytes]:
    """Returns (opcode, fin, payload) of one frame, unmasking if needed."""
    b0, b1 = await reader.readexactly(2)
    fin = bool(b0 & 0x80)
    opcode = b0 & 0x0F
    masked = bool(b1 & 0x80)
    n = b1 & 0x7F
    if n == 126:
        (n,) = struct.unpack(">H", await reader.readexactly(2))
    elif n == 127:
        (n,) = struct.unpack(">Q", await reader.readexactly(8))
    if n > max_len:
        raise TransportError(f"frame too large: {n}")
    key = await reader.readexactly(4) if masked else None
    payload = await reader.readexactly(n) if n else b""
    if key:
        payload = _mask_payload(key, payload)
    return opcode, fin, payload


async def _read_message(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    max_len: int,
    server_side: bool,
) -> Optional[bytes]:
    """One complete binary message (reassembling continuations, capped at
    ``max_len`` TOTAL), or None on CLOSE. PINGs are answered inline
    (RFC 6455 §5.5.2)."""
    buf = b""
    expecting_cont = False
    while True:
        opcode, fin, payload = await _read_frame(reader, max_len)
        if opcode == _OP_CLOSE:
            return None
        if opcode == _OP_PING:
            writer.write(_encode_frame(_OP_PONG, payload, mask=not server_side))
            await writer.drain()
            continue
        if opcode == _OP_PONG:
            continue
        if opcode == _OP_BINARY:
            if expecting_cont:  # RFC 6455 §5.4: fail the connection
                raise TransportError("new data frame arrived mid-fragmentation")
            buf = payload
        elif opcode == _OP_CONT:
            if not expecting_cont:
                raise TransportError("continuation frame without a start frame")
            if len(buf) + len(payload) > max_len:
                raise TransportError("reassembled message too large")
            buf += payload
        else:
            raise TransportError(f"unexpected ws opcode {opcode:#x}")
        if fin:
            return buf
        expecting_cont = True


async def _server_handshake(
    reader: asyncio.StreamReader, writer: asyncio.StreamWriter
) -> None:
    request = await reader.readuntil(b"\r\n\r\n")
    headers = {}
    for line in request.split(b"\r\n")[1:]:
        if b":" in line:
            k, v = line.split(b":", 1)
            headers[k.strip().lower()] = v.strip()
    key = headers.get(b"sec-websocket-key")
    if key is None or b"websocket" not in headers.get(b"upgrade", b"").lower():
        writer.write(b"HTTP/1.1 400 Bad Request\r\n\r\n")
        await writer.drain()
        raise TransportError("not a websocket upgrade request")
    writer.write(
        b"HTTP/1.1 101 Switching Protocols\r\n"
        b"Upgrade: websocket\r\n"
        b"Connection: Upgrade\r\n"
        b"Sec-WebSocket-Accept: " + _accept_key(key.decode("ascii")).encode("ascii")
        + b"\r\n\r\n"
    )
    await writer.drain()


async def _client_handshake(
    reader: asyncio.StreamReader, writer: asyncio.StreamWriter, host: str, port: int
) -> None:
    key = base64.b64encode(os.urandom(16)).decode("ascii")
    writer.write(
        (
            f"GET / HTTP/1.1\r\n"
            f"Host: {host}:{port}\r\n"
            f"Upgrade: websocket\r\n"
            f"Connection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\n"
            f"Sec-WebSocket-Version: 13\r\n\r\n"
        ).encode("ascii")
    )
    await writer.drain()
    response = await reader.readuntil(b"\r\n\r\n")
    status = response.split(b"\r\n", 1)[0]
    if b"101" not in status:
        raise TransportError(f"websocket upgrade refused: {status!r}")
    for line in response.split(b"\r\n")[1:]:
        if line.lower().startswith(b"sec-websocket-accept:"):
            got = line.split(b":", 1)[1].strip().decode("ascii")
            if got != _accept_key(key):
                raise TransportError("bad Sec-WebSocket-Accept")
            return
    raise TransportError("missing Sec-WebSocket-Accept")


class WebsocketTransport(StreamTransportBase):
    """RFC 6455 transport: one encoded message per binary frame."""

    scheme = _SCHEME

    def __init__(self, config: TransportConfig):
        super().__init__(config)

    async def _setup_inbound(self, reader, writer) -> None:
        await _server_handshake(reader, writer)

    async def _setup_outbound(self, reader, writer, host, port) -> None:
        await _client_handshake(reader, writer, host, port)

    def _frame(self, payload: bytes) -> bytes:
        # client side of the connection: RFC requires masking
        return _encode_frame(_OP_BINARY, payload, mask=True)

    async def _read_payload(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> Optional[bytes]:
        return await _read_message(
            reader, writer, self._config.max_frame_length, server_side=True
        )

    def _start_outbound_reader(self, reader, writer, conn, address) -> None:
        """The outbound channel's inbound half must be serviced: RFC 6455
        peers send PINGs (answered inside ``_read_message``) and may CLOSE;
        unread frames would otherwise rot in the stream buffer until TCP
        backpressure. Data frames a peer chooses to send back over this
        channel feed the same listen() stream as server-side ones."""

        async def _drain() -> None:
            drop_error = ""
            try:
                while not self._stopped:
                    payload = await _read_message(
                        reader, writer, self._config.max_frame_length,
                        server_side=False,
                    )
                    if payload is None:  # peer CLOSE
                        break
                    self._listeners.emit(self._codec.decode(payload))
            except (asyncio.IncompleteReadError, ConnectionResetError) as exc:
                drop_error = str(exc)
            except TransportError as exc:
                drop_error = str(exc)
                logger.warning(
                    "[%s] dropping outbound connection to %s: %s",
                    self._address, address, exc,
                )
            finally:
                # evict ONLY if the cache still points at THIS connection — a
                # stale drain racing a reconnect must not pop (and orphan)
                # its successor
                fut = self._connections.get(address)
                if (
                    fut is not None
                    and fut.done()
                    and not fut.cancelled()
                    and fut.exception() is None
                    and fut.result() is conn
                ):
                    self._connections.pop(address, None)
                    # surfaced as a transport event so churn monitors see
                    # channel loss without scraping logs; the next send()
                    # runs the bounded-backoff reconnect
                    if not self._stopped:
                        self._emit_event(
                            "connection_lost", address, error=drop_error,
                        )
                conn.close()

        conn.reader_task = asyncio.get_running_loop().create_task(_drain())


register_transport_factory("websocket", lambda cfg: WebsocketTransport(cfg))
