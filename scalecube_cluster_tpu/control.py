"""Closed-loop self-tuning control plane (r16, ROADMAP item 4).

Every sensor and actuator this module needs already exists: the telemetry
ring's per-window FD/coverage series (r8), the ``set_dissemination`` /
``set_adaptive`` live swaps (r13/r14), the tuneable gossip family's
continuous ``tuneable_mix`` knob (arXiv:1506.02288 — a family *designed*
to be tuned), and the adaptive local-health planes. What was missing is
the LOOP: a production operator of a million-member cluster cannot
hand-pick ``min_mult`` or fanout per network condition. Fault-tolerant
rumor-spreading theory (arXiv:1209.6158) gives per-condition optimal
settings; the controller's whole job is to TRACK the condition.

Design (the constraints the r6–r15 disciplines impose):

* **Pure-host policy.** The controller is a bounded hysteresis/step
  machine over host floats read from the telemetry ring at CONTROL-EPOCH
  cadence (a sync point of the same contract as a monitor poll — never
  window cadence). It adds no device code to the hot path: when it takes
  no action, an armed driver's trajectory is BIT-IDENTICAL to an unarmed
  one (pinned by tests/test_control.py), and a disarmed driver is
  untouched r15 behavior.
* **A ladder, not a continuum.** Actuation targets are discrete
  :class:`Rung`s — certified knob settings ordered from fast/cheap
  (clean network) to safe/robust (storm). The rungs' adaptive knobs are
  seeded from the OFFLINE knob map
  (``dissemination.certify.adaptive_knob_sweep`` — the r16 (min_mult ×
  conf_target × loss-floor) fp_rate_mc grid, recorded in
  CONTROL_BENCH_r16.json): per loss floor, the fastest knob whose
  false-DEAD Wilson upper bound stays within budget.
* **Bounded actuation.** One rung step per epoch at most (the clamp), a
  dwell of consecutive over-threshold epochs before moving (anti-flap;
  asymmetric — protection rises after ``dwell_up`` epochs, relaxes only
  after ``dwell_down``), and hysteresis on the way down (the condition
  must clear the rung's threshold by a margin before relaxing). The two
  FALSIFIABILITY controllers remove exactly these properties: the
  telemetry-blind controller never reads the sensors, the unclamped one
  actuates proportionally every window with no dwell, no hysteresis, and
  no rung bounds — and both must demonstrably FAIL certification
  (:func:`certify_controller_mc` records it).
* **The certification discipline applied to the controller itself.**
  :func:`certify_controller_mc` drives the controlled system through the
  r16 shifting-conditions chaos family (``chaos.shifting``: a LossStorm
  arriving mid-run, a WAN zone degrading, asymmetric loss migrating
  between regions) in scenario-batched fleet windows (``ops.fleet``),
  ≥512 seeds per cell, with per-scenario crash rows varied through the
  r16 ``FleetVary`` seam. Per scenario the SLO is joint: the clean-phase
  crash detected inside its deadline, both phase rumors spread inside
  theirs, ZERO false-DEAD of the degraded-but-alive watch cohort, and
  mean gossip cost inside the budget. The controlled arm must beat EVERY
  static rung with non-overlapping Wilson 95% intervals on P(SLO met)
  while its false-positive count is exactly zero.

Why a static setting cannot win (the physics the cells encode): fast
detection needs a low suspicion multiplier, which under ambient loss
false-kills degraded-but-alive members (the r14/r15 measured static
fp-rate of ~0.8); surviving the storm needs high multipliers and high
fanout, which blow the clean-phase detection deadline and the cost
budget. The condition SHIFTS mid-run, so only tracking it meets all four
SLOs at once.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .adaptive import AdaptiveSpec
from .dissemination.spec import DissemSpec

__all__ = [
    "Rung",
    "DEFAULT_LADDER",
    "ControlSpec",
    "ControllerState",
    "ControlSLO",
    "DEFAULT_SLO",
    "ControlPlane",
    "advance",
    "target_rung",
    "run_controlled_fleet",
    "certify_controller_mc",
]


# ---------------------------------------------------------------------------
# the ladder
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Rung:
    """One certified knob setting of the protection ladder.

    ``enter_miss_rate`` is the observed probe-miss fraction at or above
    which this rung becomes the target (rungs are checked in order, the
    highest matching wins). ``adaptive=False`` rungs run the static
    failure detector at ``static_mult`` (the clean-network fast path:
    lowest time-to-DEAD, no adaptive machinery); ``adaptive=True`` rungs
    arm the r14 plane with the listed multipliers. ``tuneable_mix`` and
    ``fanout`` steer the dissemination side (the tuneable family's knob
    and the gossip width)."""

    name: str
    enter_miss_rate: float
    tuneable_mix: float
    fanout: int
    adaptive: bool
    min_mult: int = 0
    max_mult: int = 0
    conf_target: int = 4
    static_mult: int = 3

    def adaptive_spec(self) -> AdaptiveSpec:
        if not self.adaptive:
            return AdaptiveSpec()
        return AdaptiveSpec(
            enabled=True, min_mult=self.min_mult, max_mult=self.max_mult,
            conf_target=self.conf_target,
        )

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


#: The default protection ladder, fast/cheap -> safe/robust. The adaptive
#: knobs of the degraded/storm rungs are seeded from the r16 offline knob
#: map (``adaptive_knob_sweep``, recorded in CONTROL_BENCH_r16.json): at
#: a ~10% ambient floor the fastest knob within the 3% fp budget is
#: min_mult=5 (the r14/r15 certified setting); the storm rung doubles the
#: margin (min_mult=8 — the map's recommendation once the floor or the
#: degraded cohorts push past that band). Thresholds are POST-RESCUE miss fractions — the
#: failed-probe counter counts rounds the indirect relays could not save
#: either, so the signal is small but essentially noise-free in a clean
#: network (measured at n=48, fd_every=1, ping_req_k=2: clean 0.000, 10%
#: uniform floor ~0.007, 15% ~0.026, 20% ~0.07, 25% ~0.13). The degraded
#: threshold sits ABOVE the crash-transient band: a true crash makes
#: ~1/n of probes miss (~0.021 at n=48) until the tombstone spreads, and
#: reacting to one's own detection work as if it were ambient loss would
#: reset the very suspicion doing the detecting (the confounder
#: tests/test_control.py pins; dwell_up=2 covers the band's noise tail).
DEFAULT_LADDER: Tuple[Rung, ...] = (
    Rung("clean", 0.000, tuneable_mix=0.9, fanout=2, adaptive=False,
         static_mult=3),
    Rung("degraded", 0.040, tuneable_mix=0.6, fanout=3, adaptive=True,
         min_mult=5, max_mult=10, conf_target=4),
    Rung("storm", 0.050, tuneable_mix=0.3, fanout=5, adaptive=True,
         min_mult=8, max_mult=16, conf_target=4),
)


@dataclass(frozen=True)
class ControlSpec:
    """Hashable static controller spec: the ladder + the loop constants.

    ``epoch_windows`` — windows per control epoch (sensor reads and
    decisions happen at epoch cadence). ``dwell_up`` / ``dwell_down`` —
    consecutive epochs the target must persist before actuating up /
    down the ladder (anti-flap; down is slower by design: relaxing
    protection early is the expensive mistake). ``max_step`` — rungs per
    actuation (the clamp). ``hysteresis`` — relaxing below the current
    rung requires the miss rate to fall under ``enter_miss_rate *
    hysteresis``. ``blind`` / ``clamped`` select the falsifiability
    controllers (never set in production): blind ignores the sensors
    entirely; unclamped (``clamped=False``) actuates proportionally
    every epoch with no dwell, no hysteresis, and no ladder bounds.
    """

    ladder: Tuple[Rung, ...] = DEFAULT_LADDER
    epoch_windows: int = 4
    dwell_up: int = 2
    dwell_down: int = 4
    max_step: int = 1
    hysteresis: float = 0.6
    strategy: str = "tuneable"
    topology: str = "expander"
    log_keep: int = 128
    blind: bool = False
    clamped: bool = True
    #: r19: second ladder input — ``suspect_rate`` at or above this gate
    #: votes the target ONE rung up (through the ordinary dwell_up, so it
    #: cannot flap); 0.0 keeps the sensor passive (the r16-certified
    #: single-input policy, bit-for-bit).
    suspect_gate: float = 0.0
    #: r21: third ladder input (ROADMAP item 4) — ``spread_lag`` (view
    #: dissemination deficit, see :func:`sensors_from_window`) at or above
    #: this gate votes the target ONE rung up through the same dwell_up
    #: machinery as ``suspect_gate``. 0.0 keeps it passive/logged-only.
    spread_lag_gate: float = 0.0
    #: unclamped-controller proportional gains (fanout / mult per unit
    #: miss rate) — deliberately naive high-gain tuning ("react fast"),
    #: scaled to the post-rescue sensor: a ~0.05 storm signal targets
    #: fanout ~8; see the module docstring
    unclamped_fanout_gain: float = 120.0
    unclamped_mult_gain: float = 60.0

    def __post_init__(self):
        if len(self.ladder) < 2:
            raise ValueError("a control ladder needs >= 2 rungs")
        if any(
            self.ladder[i].enter_miss_rate >= self.ladder[i + 1].enter_miss_rate
            for i in range(len(self.ladder) - 1)
        ):
            raise ValueError("ladder enter_miss_rate must strictly increase")
        if self.ladder[0].enter_miss_rate != 0.0:
            raise ValueError("the base rung must have enter_miss_rate == 0")
        if self.epoch_windows < 1:
            raise ValueError("epoch_windows must be >= 1")
        if self.dwell_up < 1 or self.dwell_down < 1:
            raise ValueError("dwell epochs must be >= 1")
        if self.max_step < 1:
            raise ValueError("max_step must be >= 1")
        if not (0.0 < self.hysteresis <= 1.0):
            raise ValueError("hysteresis must be in (0, 1]")
        if self.suspect_gate < 0.0:
            raise ValueError("suspect_gate must be >= 0 (0 disables it)")
        if self.spread_lag_gate < 0.0:
            raise ValueError("spread_lag_gate must be >= 0 (0 disables it)")

    @staticmethod
    def from_config(config) -> "ControlSpec":
        """Map a ``ClusterConfig.control`` block (or an absent one)."""
        cc = getattr(config, "control", None)
        if cc is None:
            return ControlSpec()
        return ControlSpec(
            epoch_windows=cc.epoch_windows,
            dwell_up=cc.dwell_up,
            dwell_down=cc.dwell_down,
            max_step=cc.max_step,
            hysteresis=cc.hysteresis,
            suspect_gate=getattr(cc, "suspect_gate", 0.0),
            spread_lag_gate=getattr(cc, "spread_lag_gate", 0.0),
        )


# ---------------------------------------------------------------------------
# controller state + the decision rule (ONE spelling for the driver plane
# and the fleet certification harness)
# ---------------------------------------------------------------------------


@dataclass
class ControllerState:
    """Host-side controller memory (checkpointable — see ``state_dict``)."""

    rung: int = 0
    #: whether any actuation has happened yet (arming is knob-passive:
    #: the bit-identity contract — knobs change only on a decision)
    actuated: bool = False
    epoch: int = 0
    windows: int = 0
    pend_target: Optional[int] = None
    pend_count: int = 0
    actuations: int = 0
    stale_epochs: int = 0
    last_sensors: Optional[dict] = None
    log: List[dict] = field(default_factory=list)

    def state_dict(self) -> dict:
        return {
            "rung": self.rung,
            "actuated": self.actuated,
            "epoch": self.epoch,
            "windows": self.windows,
            "pend_target": self.pend_target,
            "pend_count": self.pend_count,
            "actuations": self.actuations,
            "stale_epochs": self.stale_epochs,
            "last_sensors": self.last_sensors,
            "log": list(self.log),
        }

    @staticmethod
    def from_state_dict(d: dict) -> "ControllerState":
        st = ControllerState()
        for k in ("rung", "actuated", "epoch", "windows", "pend_target",
                  "pend_count", "actuations", "stale_epochs", "last_sensors"):
            setattr(st, k, d[k])
        st.log = list(d.get("log", ()))
        return st


def sensors_from_window(ms_sums: dict) -> dict:
    """Host sensor vector from one epoch's summed window counters
    (``fd_probes``/``fd_failed_probes``/``fd_new_suspects`` — the exact
    names of the engines' shared metric series). ``miss_rate`` is the
    round-trip probe miss fraction — the ambient-loss proxy;
    ``suspect_rate`` is new suspicions per probe — the false-positive
    pressure proxy; ``spread_lag`` (r21, ROADMAP item 4) is the view
    dissemination deficit ``convergence_lag``, guarded by
    ``alive_view_fraction > 0``: engines running ``full_metrics=False``
    report that fraction as a constant 0 (the lag column is then a
    constant 1.0, not a measurement), so the sensor stays 0/passive there
    instead of tripping permanently."""
    probes = float(ms_sums.get("fd_probes", 0.0))
    failed = float(ms_sums.get("fd_failed_probes", 0.0))
    suspects = float(ms_sums.get("fd_new_suspects", 0.0))
    alive_frac = float(ms_sums.get("alive_view_fraction", 0.0))
    spread_lag = (
        float(ms_sums.get("convergence_lag", 0.0)) if alive_frac > 0.0 else 0.0
    )
    return {
        "miss_rate": failed / max(probes, 1.0),
        "suspect_rate": suspects / max(probes, 1.0),
        "spread_lag": spread_lag,
        "probes": probes,
    }


def target_rung(spec: ControlSpec, miss_rate: float, current: int) -> int:
    """The ladder rung the observed miss rate calls for, WITH hysteresis:
    stepping below ``current`` additionally requires the miss rate to
    clear ``current``'s threshold by the hysteresis margin."""
    t = 0
    for i, r in enumerate(spec.ladder):
        if miss_rate >= r.enter_miss_rate:
            t = i
    if t < current and miss_rate >= (
        spec.ladder[current].enter_miss_rate * spec.hysteresis
    ):
        t = current
    return t


def _proportional_rung(spec: ControlSpec, miss_rate: float) -> Rung:
    """The UNCLAMPED falsifiability controller's naive proportional law:
    no ladder, no bounds — fanout and suspicion multipliers scale
    linearly with the instantaneous miss rate. Overshoots the cost
    budget under a real storm and re-targets on every quantization
    wiggle; exists to PROVE the clamp/dwell matter (it must fail
    certification)."""
    fanout = 2 + int(round(spec.unclamped_fanout_gain * miss_rate))
    min_mult = 3 + int(round(spec.unclamped_mult_gain * miss_rate))
    adaptive = min_mult > 3
    return Rung(
        name=f"prop-f{fanout}-m{min_mult}",
        enter_miss_rate=0.0,
        tuneable_mix=max(0.0, round(0.9 - 2.5 * miss_rate, 2)),
        fanout=fanout,
        adaptive=adaptive,
        min_mult=min_mult,
        max_mult=2 * min_mult,
        conf_target=4,
        static_mult=min_mult if not adaptive else 3,
    )


def advance(
    spec: ControlSpec,
    st: ControllerState,
    sensors: Optional[dict],
    tick: Optional[int] = None,
) -> Optional[Rung]:
    """One control epoch of the decision rule — THE policy spelling,
    shared by the driver :class:`ControlPlane` and the fleet
    certification harness. Mutates ``st`` (epoch counters, dwell state,
    decision log) and returns the :class:`Rung` to actuate, or None.

    ``sensors=None`` is SENSOR DROPOUT (empty/stale telemetry ring): the
    controller holds the last safe setting and logs the dropout — it
    never acts on missing evidence."""
    st.epoch += 1

    def log(action: str, reason: str, **extra):
        st.log.append({
            "epoch": st.epoch, "tick": tick, "rung": st.rung,
            "rung_name": (
                spec.ladder[st.rung].name
                if st.rung < len(spec.ladder) else "proportional"
            ),
            "action": action, "reason": reason,
            "miss_rate": (
                round(sensors["miss_rate"], 4) if sensors else None
            ),
            "suspect_rate": (
                round(sensors.get("suspect_rate", 0.0), 4)
                if sensors else None
            ),
            "spread_lag": (
                round(sensors.get("spread_lag", 0.0), 4)
                if sensors else None
            ),
            **extra,
        })
        if len(st.log) > spec.log_keep:
            del st.log[: len(st.log) - spec.log_keep]

    if sensors is None:
        st.stale_epochs += 1
        st.pend_target, st.pend_count = None, 0
        log("hold", "sensors_stale")
        return None
    st.last_sensors = dict(sensors)
    miss = spec.ladder[0].enter_miss_rate if spec.blind else sensors["miss_rate"]

    if not spec.clamped:
        rung = _proportional_rung(spec, miss)
        prev = st.log[-1].get("knobs") if st.log else None
        knobs = rung.as_dict()
        if knobs != prev:
            st.actuated = True
            st.actuations += 1
            log("actuate", "proportional", knobs=knobs)
            return rung
        log("hold", "proportional_unchanged", knobs=knobs)
        return None

    target = target_rung(spec, miss, st.rung)
    if spec.blind:
        # never reads the ring: the target is forever the base rung
        target = 0 if not st.actuated else st.rung
    elif (
        spec.suspect_gate > 0.0
        and sensors.get("suspect_rate", 0.0) >= spec.suspect_gate
        and target <= st.rung
    ):
        # r19 second ladder input: false-positive pressure (suspect_rate)
        # votes the target ONE rung up. Up-only by construction — it can
        # never lower a miss-rate target — and the vote still rides the
        # ordinary dwell_up/pend machinery, so a transient suspicion burst
        # cannot flap a certified rung (test_control pins this).
        target = min(st.rung + 1, len(spec.ladder) - 1)
    elif (
        spec.spread_lag_gate > 0.0
        and sensors.get("spread_lag", 0.0) >= spec.spread_lag_gate
        and target <= st.rung
    ):
        # r21 third ladder input (ROADMAP item 4): dissemination spread
        # lag votes ONE rung up, same up-only + dwell_up construction as
        # the suspect gate (elif: the gates are votes for the SAME
        # one-rung step, never additive).
        target = min(st.rung + 1, len(spec.ladder) - 1)
    if target == st.rung:
        st.pend_target, st.pend_count = None, 0
        log("hold", "at_target")
        return None
    if st.pend_target == target:
        st.pend_count += 1
    else:
        st.pend_target, st.pend_count = target, 1
    need = spec.dwell_up if target > st.rung else spec.dwell_down
    if st.pend_count < need:
        log("dwell", "waiting", target=target, pending=st.pend_count,
            need=need)
        return None
    step = max(-spec.max_step, min(spec.max_step, target - st.rung))
    st.rung += step
    st.actuated = True
    st.actuations += 1
    if st.rung == target:
        st.pend_target, st.pend_count = None, 0
    else:
        # clamped mid-move: keep the dwell satisfied so the next epoch
        # continues the walk one rung at a time
        st.pend_count = need
    rung = spec.ladder[st.rung]
    log("actuate", "step", target=target, step=step, knobs=rung.as_dict())
    return rung


# ---------------------------------------------------------------------------
# the driver-attached plane
# ---------------------------------------------------------------------------


class ControlPlane:
    """The closed loop on one :class:`..sim.SimDriver`.

    Arming requires (and auto-arms) the telemetry plane — the ring is the
    sensor. Every ``epoch_windows`` windows the plane reads the newest
    ring row (ONE coalesced device readback at epoch cadence — the same
    sync-point contract as a monitor poll), runs :func:`advance`, and on
    a decision applies the target rung through the driver's live-swap
    actuators (``set_dissemination`` / ``set_protocol_knobs`` /
    ``set_adaptive``). With no decision the driver's trajectory is
    bit-identical to an unarmed one. ``snapshot()`` backs the monitor's
    ``GET /control``."""

    def __init__(self, driver, spec: Optional[ControlSpec] = None,
                 config=None):
        from .config import ClusterConfig

        if spec is None:
            spec = (
                ControlSpec.from_config(config)
                if isinstance(config, ClusterConfig) else ControlSpec()
            )
        if spec.blind or not spec.clamped:
            raise ValueError(
                "the blind/unclamped falsifiability controllers exist only "
                "for certification (certify_controller_mc) — refusing to "
                "arm one on a live driver"
            )
        self.driver = driver
        self.spec = spec
        self.state = ControllerState()
        self._ring_windows_seen = 0
        self._telemetry = driver.arm_telemetry(config=config)
        self._telemetry.bus.publish(
            "control", "control_armed", tick=driver._host_tick,
            ladder=[r.name for r in spec.ladder],
            epoch_windows=spec.epoch_windows,
        )

    # -- the loop ------------------------------------------------------------
    def on_window(self) -> None:
        """Called by the driver after each window (under the driver
        lock). Cheap counter bump except at epoch boundaries."""
        self.state.windows += 1
        if self.state.windows % self.spec.epoch_windows:
            return
        self._run_epoch()

    def _run_epoch(self) -> None:
        d = self.driver
        sensors = self._read_sensors()
        rung = advance(self.spec, self.state, sensors, tick=d._host_tick)
        if rung is not None:
            self._apply_rung(rung)
            self._telemetry.bus.publish(
                "control", "actuated", tick=d._host_tick,
                rung=rung.name, fanout=rung.fanout,
                tuneable_mix=rung.tuneable_mix,
                adaptive=rung.adaptive, min_mult=rung.min_mult,
            )

    def _read_sensors(self) -> Optional[dict]:
        """Newest ring row -> sensor vector; None on dropout (empty ring
        or no new window since the last epoch — the stale-sensor hold)."""
        ring = self._telemetry.ring
        if ring.windows == 0 or ring.windows == self._ring_windows_seen:
            return None
        self._ring_windows_seen = ring.windows
        vals = ring.latest_values()  # the one epoch-cadence readback
        self.driver._note_readback(1)
        if not vals:
            return None
        return sensors_from_window(vals)

    def _apply_rung(self, rung: Rung) -> None:
        d = self.driver
        d.set_dissemination(
            strategy=self.spec.strategy, topology=self.spec.topology,
            tuneable_mix=rung.tuneable_mix,
        )
        d.set_protocol_knobs(
            fanout=rung.fanout,
            suspicion_mult=None if rung.adaptive else rung.static_mult,
        )
        if rung.adaptive:
            d.set_adaptive(rung.adaptive_spec())
        else:
            d.set_adaptive(None)

    # -- surfaces ------------------------------------------------------------
    def snapshot(self) -> dict:
        """The ``GET /control`` view: spec summary + controller state +
        the bounded decision log (newest last). Host values only."""
        st = self.state
        rung = (
            self.spec.ladder[st.rung]
            if st.rung < len(self.spec.ladder) else None
        )
        return {
            "armed": True,
            "epoch_windows": self.spec.epoch_windows,
            "dwell_up": self.spec.dwell_up,
            "dwell_down": self.spec.dwell_down,
            "max_step": self.spec.max_step,
            "hysteresis": self.spec.hysteresis,
            "ladder": [r.as_dict() for r in self.spec.ladder],
            "rung": st.rung,
            "rung_name": rung.name if rung else None,
            "actuated": st.actuated,
            "epoch": st.epoch,
            "windows": st.windows,
            "actuations": st.actuations,
            "stale_epochs": st.stale_epochs,
            "pending": {"target": st.pend_target, "count": st.pend_count},
            "last_sensors": st.last_sensors,
            "decision_log": list(st.log),
        }

    def state_dict(self) -> dict:
        return self.state.state_dict()

    def load_state_dict(self, d: dict) -> None:
        """Restore controller memory (the checkpoint/restore seam). An
        ACTUATED state re-applies its rung's knobs — the restored driver
        was constructed with its own params, not the actuated ones."""
        self.state = ControllerState.from_state_dict(d)
        self._ring_windows_seen = 0  # the restored ring is a new timeline
        if self.state.actuated and self.state.rung < len(self.spec.ladder):
            self._apply_rung(self.spec.ladder[self.state.rung])

    def reset_for_restore(self) -> None:
        """Restore from a checkpoint carrying NO controller state: the
        abandoned branch's memory (rung, dwell, decision log) must not
        survive the timeline switch — same invariant as every other
        plane's restore. If that branch had ACTUATED, the knobs re-base
        to the ladder's base rung so rung and params agree again
        (construction params are not recoverable once an actuation
        swapped them); a never-actuated plane stays knob-passive."""
        was_actuated = self.state.actuated
        self.state = ControllerState()
        self._ring_windows_seen = 0  # the restored ring is a new timeline
        if was_actuated:
            self._apply_rung(self.spec.ladder[0])


# ---------------------------------------------------------------------------
# fleet certification harness (the r15 MC service closed over the loop)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ControlSLO:
    """The joint per-scenario SLO of the controller certification.

    Deadlines are in ticks: detection from the crash, spread from each
    rumor's injection (clean phase / shifted phase separately — the
    shifted network is allowed more). ``cost_budget`` bounds the mean
    gossip messages per member-tick over the WHOLE run — the envelope
    that makes permanent max-protection (and the unclamped controller's
    overshoot) a certification failure, exactly as in production."""

    detect_deadline: int = 32
    spread_clean_deadline: int = 40
    spread_shift_deadline: int = 32
    cost_budget: float = 2.6


DEFAULT_SLO = ControlSLO()


def _fleet_params(n: int, rung: Rung, spec: ControlSpec):
    """The dense fleet-profile params of one knob setting (the static
    program a rung compiles to)."""
    from .ops.state import SimParams

    return SimParams(
        capacity=n, fanout=rung.fanout, fd_every=1, sync_every=40,
        suspicion_mult=rung.static_mult, rumor_slots=8, seed_rows=(0,),
        full_metrics=False, quiet_gates=False,
        dissem=DissemSpec(
            strategy=spec.strategy, topology=spec.topology,
            tuneable_mix=rung.tuneable_mix,
        ),
        adaptive=rung.adaptive_spec(),
    )


def run_controlled_fleet(
    shifting,
    arm: str = "controlled",
    *,
    n: int = 48,
    n_seeds: int = 512,
    window: int = 8,
    base_seed: int = 0,
    spec: Optional[ControlSpec] = None,
    slo: ControlSLO = DEFAULT_SLO,
    static_rung: Optional[int] = None,
    vary_storm_pct=None,
    conf: float = 0.95,
) -> dict:
    """Drive ``n_seeds`` scenarios of one shifting-conditions cell
    (:class:`..chaos.shifting.ShiftingScenario`) through fleet windows
    with one of the certification arms at the wheel:

    * ``"controlled"`` — the clamped/dwelled ladder controller;
    * ``"static"`` — rung ``static_rung`` held for the whole run;
    * ``"blind"`` — the telemetry-blind falsifiability controller;
    * ``"unclamped"`` — the proportional falsifiability controller.

    The controller observes the FLEET-AGGREGATE sensors (knobs are
    static program properties shared across the scenario axis, so the
    shared policy acts on the fleet mean — one scalar readback per
    epoch, certification-harness cadence). ``spec.epoch_windows`` is
    honored exactly as by :class:`ControlPlane`: the decision rule runs
    every ``epoch_windows``-th fleet window on the NEWEST window's
    sensors (the plane reads only the newest ring row). The default
    certification spec pins ``epoch_windows=1`` — one ``window``-tick
    fleet window per control epoch, the cadence the artifact records as
    ``epoch_ticks``. A knob change swaps the compiled fleet program for
    the new setting's and — mirroring ``SimDriver.set_adaptive``
    exactly — RESETS the adaptive evidence planes ("a knob change is a
    new experiment"); engine state, key chains, and rumor planes carry
    over untouched.

    Per-scenario crash rows vary through :class:`..ops.fleet.FleetVary`
    (and optionally the storm floor, via ``vary_storm_pct`` — the
    condition grid one fleet sweeps); rumor origins and PRNG chains vary
    per scenario as in every r15 MC service. All SLO folds stay on
    device; the [S] readbacks happen once at the end."""
    import jax
    import jax.numpy as jnp

    from .adaptive import init_adaptive_state
    from .ops import fleet as FL
    from .ops import state as S

    spec = spec or ControlSpec(epoch_windows=1)
    if arm == "blind":
        spec = dataclasses.replace(spec, blind=True)
    elif arm == "unclamped":
        spec = dataclasses.replace(spec, clamped=False)
    elif arm == "static":
        if static_rung is None or not 0 <= static_rung < len(spec.ladder):
            raise ValueError("static arm needs static_rung in the ladder")
    elif arm != "controlled":
        raise ValueError(f"unknown arm {arm!r}")

    scen = shifting.scenario
    horizon = scen.horizon
    crash_at = shifting.crash_at
    seeds = base_seed + np.arange(n_seeds)
    # per-scenario crash rows: a block disjoint from the watch cohort,
    # the seed row, and each other SLO subject (r16 FleetVary)
    forbidden = set(shifting.watch_rows) | {0}
    crash_pool = [r for r in range(12, n) if r not in forbidden][:8]
    crash_rows = np.asarray([crash_pool[s % len(crash_pool)] for s in range(n_seeds)])
    vary = FL.FleetVary(
        crash_rows=crash_rows,
        loss_pct=(
            np.asarray(vary_storm_pct, np.float32)[
                np.arange(n_seeds) % len(vary_storm_pct)
            ]
            if vary_storm_pct is not None else None
        ),
    )

    init_rung = spec.ladder[static_rung if arm == "static" else 0]
    cur_rung = init_rung
    ctl = ControllerState(rung=(static_rung if arm == "static" else 0))

    # program + params caches, keyed on the knob setting
    progs: Dict[tuple, object] = {}
    params_cache: Dict[tuple, object] = {}

    def _key_of(r: Rung):
        return (r.tuneable_mix, r.fanout, r.adaptive, r.min_mult,
                r.max_mult, r.conf_target, r.static_mult)

    def _params(r: Rung):
        k = _key_of(r)
        if k not in params_cache:
            params_cache[k] = _fleet_params(n, r, spec)
        return params_cache[k]

    def _prog(r: Rung, k_ticks: int):
        k = (_key_of(r), k_ticks)
        if k not in progs:
            p = _params(r)
            progs[k] = (
                FL.make_fleet_adaptive_run(p, k_ticks) if r.adaptive
                else FL.make_fleet_run(p, k_ticks)
            )
        return progs[k]

    st0 = S.init_state(_params(init_rung), n, warm=True)
    fs = FL.fleet_broadcast(st0, n_seeds)
    keys = FL.fleet_keys(1000 + seeds)
    ad = (
        FL.fleet_broadcast(init_adaptive_state(n), n_seeds)
        if init_rung.adaptive else None
    )
    tl = FL.fleet_timeline(scen, S, dense_links=True, horizon=horizon,
                           vary=vary)

    rumor_plan = dict((t, slot) for slot, t in shifting.rumors)
    origins = {slot: (seeds * 37 + 11 * (slot + 1)) % n
               for slot, _t in shifting.rumors}
    hits = {slot: jnp.full((n_seeds,), -1, jnp.int32)
            for slot, _t in shifting.rumors}
    fp_max = jnp.zeros((n_seeds,), jnp.int32)
    det_tick = jnp.full((n_seeds,), -1, jnp.int32)
    cost_sum = jnp.zeros((n_seeds,), jnp.float32)
    watch_mask = np.zeros((n,), bool)
    watch_mask[list(shifting.watch_rows)] = True
    watch_mask = jnp.asarray(watch_mask)
    crash_rows_dev = jnp.asarray(crash_rows, jnp.int32)

    fold_cov = jax.jit(FL.fold_first_full_coverage)
    fold_fp = jax.jit(FL.fleet_false_dead)
    fold_det = jax.jit(
        lambda st: FL.fleet_crash_detected_varied(st, crash_rows_dev)
    )
    # fleet-aggregate sensor sums + per-scenario cost, one fused reduce
    fold_sense = jax.jit(lambda ms: (
        ms["fd_probes"].sum(), ms["fd_failed_probes"].sum(),
        ms["fd_new_suspects"].sum(), ms["gossip_msgs"].sum(axis=1),
    ))

    boundaries = set(tl.boundaries()) | set(rumor_plan)
    knob_log: List[dict] = []
    t = 0
    windows_run = 0
    while t < horizon:
        fs, _labels = tl.apply_due(fs, t)
        if t in rumor_plan:
            slot = rumor_plan[t]
            fs = FL.fleet_inject_rumor(S, fs, slot, origins[slot])
        stop = min(
            [horizon, t + window] + [b for b in boundaries if b > t]
        )
        k_ticks = stop - t
        if cur_rung.adaptive:
            fs, ad, keys, ms, _w = _prog(cur_rung, k_ticks)(fs, ad, keys)
        else:
            fs, keys, ms, _w = _prog(cur_rung, k_ticks)(fs, keys)
        for slot in hits:
            hits[slot] = fold_cov(
                hits[slot], ms["rumor_coverage"][:, :, slot], t
            )
        probes, failed, suspects, cost_w = fold_sense(ms)
        cost_sum = cost_sum + cost_w
        t = stop
        fp_max = jnp.maximum(fp_max, fold_fp(fs, watch_mask))
        if t > crash_at:
            det = fold_det(fs)
            det_tick = jnp.where((det_tick < 0) & det, jnp.int32(t), det_tick)
        windows_run += 1
        if arm != "static" and windows_run % spec.epoch_windows == 0:
            # the control epoch: fleet-mean sensors from the NEWEST
            # window (mirroring ControlPlane._read_sensors — the plane
            # reads only the newest ring row), the shared decision rule,
            # a program swap on actuation
            sensors = sensors_from_window({
                "fd_probes": float(probes),
                "fd_failed_probes": float(failed),
                "fd_new_suspects": float(suspects),
            })
            new_rung = advance(spec, ctl, sensors, tick=t)
            if new_rung is not None and _key_of(new_rung) != _key_of(cur_rung):
                knob_log.append({
                    "tick": t, "from": cur_rung.name, "to": new_rung.name,
                    "miss_rate": round(sensors["miss_rate"], 4),
                })
                was_adaptive = cur_rung.adaptive
                cur_rung = new_rung
                if cur_rung.adaptive:
                    # set_adaptive semantics: arming OR changing knobs
                    # starts fresh evidence (scores describe the current
                    # conditions under the current knobs)
                    ad = FL.fleet_broadcast(init_adaptive_state(n), n_seeds)
                elif was_adaptive:
                    ad = None

    fs, _labels = tl.apply_due(fs, horizon)
    # THE readbacks: one [S] vector per fold
    fp_np = np.asarray(fp_max)
    det_np = np.asarray(det_tick)
    cost_np = np.asarray(cost_sum) / float(horizon * n)
    hit_np = {slot: np.asarray(v) for slot, v in hits.items()}

    inject = dict((slot, t) for slot, t in shifting.rumors)
    shift_at = shifting.shift_at
    ok_detect = (det_np >= 0) & (det_np - crash_at <= slo.detect_deadline)
    ok_fp = fp_np == 0
    ok_cost = cost_np <= slo.cost_budget
    ok_spread = np.ones((n_seeds,), bool)
    spread_stats = {}
    for slot, t0 in inject.items():
        deadline = (
            slo.spread_clean_deadline if t0 < shift_at
            else slo.spread_shift_deadline
        )
        h = hit_np[slot]
        ok = (h >= 0) & (h - t0 <= deadline)
        ok_spread &= ok
        lat = np.sort(h[h >= 0] - t0)
        spread_stats[str(slot)] = {
            "inject_tick": int(t0),
            "deadline": int(deadline),
            "finished": int((h >= 0).sum()),
            "met": int(ok.sum()),
            "p50": float(np.median(lat)) if lat.size else None,
            "max": int(lat[-1]) if lat.size else None,
        }
    ok_all = ok_detect & ok_fp & ok_cost & ok_spread
    k = int(ok_all.sum())
    from .dissemination.certify import MC_MIN_SAMPLES, wilson_interval

    wil = wilson_interval(k, n_seeds, conf)
    det_lat = np.sort(det_np[det_np >= 0] - crash_at)
    return {
        "arm": arm + (f"-{spec.ladder[static_rung].name}"
                      if arm == "static" else ""),
        "scenario": shifting.name,
        "n": n,
        "n_seeds": n_seeds,
        "sample_size": n_seeds,
        "verdict_kind": (
            "monte-carlo" if n_seeds >= MC_MIN_SAMPLES else "spot-check"
        ),
        "window_ticks": window,
        "epoch_windows": spec.epoch_windows,
        "epoch_ticks": spec.epoch_windows * window,
        "slo": dataclasses.asdict(slo),
        "slo_met": k,
        "p_slo": round(k / n_seeds, 6),
        "slo_wilson": [round(wil[0], 6), round(wil[1], 6)],
        "interval_method": f"Wilson {conf:.0%} on P(all SLOs met)",
        "fail_detect": int((~ok_detect).sum()),
        "fail_fp": int((~ok_fp).sum()),
        "fail_cost": int((~ok_cost).sum()),
        "fail_spread": int((~ok_spread).sum()),
        "false_dead_scenarios": int((fp_np > 0).sum()),
        "detect_latency_p50": (
            float(np.median(det_lat)) if det_lat.size else None
        ),
        "detect_latency_max": int(det_lat[-1]) if det_lat.size else None,
        "cost_mean": round(float(cost_np.mean()), 4),
        "cost_max": round(float(cost_np.max()), 4),
        "spread": spread_stats,
        "actuations": ctl.actuations,
        "stale_epochs": ctl.stale_epochs,
        "knob_changes": knob_log,
        "decision_log_tail": ctl.log[-16:],
        "crash_rows_varied": sorted(set(crash_rows.tolist())),
        "storm_pct_varied": (
            sorted({float(p) for p in np.asarray(vary_storm_pct)})
            if vary_storm_pct is not None else None
        ),
    }


def certify_controller_mc(
    cells: Optional[Sequence] = None,
    n: int = 48,
    n_seeds: int = 512,
    window: int = 8,
    base_seed: int = 0,
    spec: Optional[ControlSpec] = None,
    slo: ControlSLO = DEFAULT_SLO,
    vary_storm_pct=None,
    log=None,
    bus=None,
) -> dict:
    """The r16 controller certification matrix: for every shifting-
    conditions cell, run the CONTROLLED arm, every STATIC rung of its own
    ladder, and both falsifiability controllers, ≥``n_seeds`` seeds each
    (one fleet program per arm per knob setting).

    A cell CERTIFIES when (a) the controlled arm's Wilson lower bound on
    P(all SLOs met) strictly exceeds every static arm's Wilson upper
    bound — the controller beats every setting it is allowed to pick,
    so the VALUE IS IN THE SWITCHING — (b) the controlled arm records
    zero false-DEAD, and (c) both falsifiability arms FAIL the same
    criteria (seeded falsifiability, the r12/r14 discipline: a
    certification that cannot fail proves nothing). Returns the record
    ``benchmarks/config15_control.py`` writes into
    CONTROL_BENCH_r16.json.

    The default certification spec pins ``epoch_windows=1``: one
    ``window``-tick fleet window per control epoch (the harness honors
    the knob; the record's ``epoch_ticks`` states the exercised
    cadence). A driver-attached :class:`ControlPlane` counts DRIVER
    windows instead, so its epoch duration is caller-dependent —
    certify at the cadence you deploy."""
    from .chaos import shifting as _shifting

    spec = spec or ControlSpec(epoch_windows=1)
    if cells is None:
        cells = [b(n=n) for b in _shifting.SHIFTING_FAMILY]
    entries = []
    for cell in cells:
        arms = {}

        def _run(arm, **kw):
            rec = run_controlled_fleet(
                cell, arm, n=n, n_seeds=n_seeds, window=window,
                base_seed=base_seed, spec=spec, slo=slo,
                vary_storm_pct=vary_storm_pct, **kw,
            )
            arms[rec["arm"]] = rec
            if log:
                log(
                    f"{cell.name}/{rec['arm']}: P(SLO) {rec['p_slo']} "
                    f"wilson {rec['slo_wilson']} fp {rec['false_dead_scenarios']} "
                    f"cost {rec['cost_mean']} "
                    f"fails d/f/c/s {rec['fail_detect']}/{rec['fail_fp']}/"
                    f"{rec['fail_cost']}/{rec['fail_spread']}"
                )
            return rec

        controlled = _run("controlled")
        statics = [
            _run("static", static_rung=i) for i in range(len(spec.ladder))
        ]
        blind = _run("blind")
        unclamped = _run("unclamped")

        max_static_hi = max(r["slo_wilson"][1] for r in statics)

        def _would_certify(rec):
            return (
                rec["slo_wilson"][0] > max_static_hi
                and rec["false_dead_scenarios"] == 0
            )

        certified = _would_certify(controlled)
        blind_fails = not _would_certify(blind)
        unclamped_fails = not _would_certify(unclamped)
        entry = {
            "cell": cell.name,
            "phases": list(map(list, cell.phases)),
            "arms": arms,
            "controlled_wilson": controlled["slo_wilson"],
            "best_static_wilson_hi": round(max_static_hi, 6),
            "separation": round(
                controlled["slo_wilson"][0] - max_static_hi, 6
            ),
            "controlled_false_dead": controlled["false_dead_scenarios"],
            "blind_fails_certification": blind_fails,
            "unclamped_fails_certification": unclamped_fails,
            "unclamped_actuations": unclamped["actuations"],
            "controlled_actuations": controlled["actuations"],
            "certified": bool(
                certified and blind_fails and unclamped_fails
            ),
        }
        entries.append(entry)
        if log:
            log(
                f"{cell.name}: separation {entry['separation']} "
                f"blind_fails={blind_fails} unclamped_fails={unclamped_fails} "
                f"{'CERTIFIED' if entry['certified'] else 'VIOLATION'}"
            )
        if bus is not None:
            bus.publish(
                "control", "controller_certified",
                cell=cell.name, certified=entry["certified"],
                controlled_wilson=entry["controlled_wilson"],
                best_static_wilson_hi=entry["best_static_wilson_hi"],
            )
    return {
        "n": n,
        "n_seeds": n_seeds,
        "window_ticks": window,
        "slo": dataclasses.asdict(slo),
        "ladder": [r.as_dict() for r in spec.ladder],
        "epoch_windows": spec.epoch_windows,
        "epoch_ticks": spec.epoch_windows * window,
        "dwell_up": spec.dwell_up,
        "dwell_down": spec.dwell_down,
        "hysteresis": spec.hysteresis,
        "entries": entries,
        "n_certified": sum(1 for e in entries if e["certified"]),
        "n_cells": len(entries),
        "ok": all(e["certified"] for e in entries),
    }
