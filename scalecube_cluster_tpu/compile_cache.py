"""Persistent XLA compilation cache wiring + in-process jit audit.

The flagship program (98,304 members / 8-way mesh) recompiles from scratch
in every process — 51 minutes of the r5 flagship wall clock was
non-overlapped compile + execute (``FLAGSHIP_EXEC_r05.json``). XLA ships a
persistent on-disk compilation cache keyed on the lowered HLO (which covers
capacity, mesh shape, and every static ``SimParams``/``SparseParams`` knob,
since they are all baked into the traced program); enabling it makes
repeated bench runs and flagship re-executions skip compilation entirely.

Two layers, both exposed here:

* :func:`enable_persistent_compile_cache` — point JAX at a cache directory
  (``ClusterConfig.sim.compile_cache_dir`` > ``SCALECUBE_COMPILE_CACHE_DIR``
  env > explicit argument). Safe to call late: JAX latches its
  "is the cache usable" decision at the first compile, so this resets that
  latch when supported.
* :func:`compile_cache_report` — what the on-disk cache currently holds
  (entry count + bytes), for bench artifacts and the monitor audit.

The in-process side (which jitted window programs exist, how often each was
dispatched, what the first dispatch cost) lives on the driver:
``SimDriver.jit_cache_audit()`` merges its ``_step_cache`` stats with this
module's on-disk report.
"""

from __future__ import annotations

import os
import pathlib
import stat as _stat
import time
from typing import Any, Dict, Optional

ENV_VAR = "SCALECUBE_COMPILE_CACHE_DIR"

_enabled_dir: Optional[str] = None


def resolve_cache_dir(cache_dir: Optional[str] = None, config=None) -> Optional[str]:
    """Resolution order: explicit arg > ``config.sim.compile_cache_dir`` >
    ``SCALECUBE_COMPILE_CACHE_DIR`` env. None means "leave disabled"."""
    if cache_dir:
        return cache_dir
    if config is not None:
        sim = getattr(config, "sim", None)
        if sim is not None and getattr(sim, "compile_cache_dir", None):
            return sim.compile_cache_dir
    return os.environ.get(ENV_VAR) or None


def enable_persistent_compile_cache(
    cache_dir: Optional[str] = None, config=None
) -> Optional[str]:
    """Enable JAX's persistent compilation cache at the resolved directory.

    Returns the directory in effect (created if missing), or None when no
    directory is configured anywhere — in which case nothing changes.
    Thresholds are dropped to zero so even the small test-size programs
    cache (the default gates skip sub-second compiles, which would make the
    cache look broken in smoke runs). Idempotent; never raises on an older
    jax without the knobs (the cache is then simply not enabled).
    """
    global _enabled_dir
    path = resolve_cache_dir(cache_dir, config)
    if not path:
        return None
    if _enabled_dir == path:
        return path
    import jax

    pathlib.Path(path).mkdir(parents=True, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception:  # noqa: BLE001 — knob varies across jax versions
        return None
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:  # noqa: BLE001 — thresholds are an optimization only:
        pass  # the directory alone enables caching (default gates apply)
    # JAX latches cache usability at the FIRST compile of the process; if
    # anything compiled before this call (a warmup op, another module's
    # import-time jit), the latch reads "no cache dir" forever. Reset it so
    # late enabling still takes effect; best-effort across jax versions.
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # noqa: BLE001
        pass
    _enabled_dir = path
    return path


def enabled_cache_dir() -> Optional[str]:
    """The directory a successful enable call put in effect (None if never)."""
    return _enabled_dir


def compile_cache_report(cache_dir: Optional[str] = None) -> Dict[str, Any]:
    """On-disk cache audit: entry count, total bytes, newest entry age.

    The directory actually ENABLED takes precedence over the env var — an
    audit must describe the cache in effect, not a configured-but-unused
    one. One stat per entry (this runs on every /dispatch poll)."""
    path = cache_dir or _enabled_dir or resolve_cache_dir(None)
    if not path or not os.path.isdir(path):
        return {"enabled": _enabled_dir is not None, "dir": path, "entries": 0,
                "total_bytes": 0}
    stats = []
    for p in pathlib.Path(path).iterdir():
        try:
            s = p.stat()
        except OSError:  # entry evicted/renamed by a concurrent process
            continue
        if _stat.S_ISREG(s.st_mode):
            stats.append(s)
    newest = max((s.st_mtime for s in stats), default=0.0)
    return {
        "enabled": _enabled_dir == path,
        "dir": path,
        "entries": len(stats),
        "total_bytes": int(sum(s.st_size for s in stats)),
        "newest_entry_age_s": round(time.time() - newest, 1) if stats else None,
    }
