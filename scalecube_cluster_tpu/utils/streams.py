"""Hot pub-sub event stream (the Reactor ``Flux``/``Sinks`` analogue).

Subscribers are sync callbacks invoked in subscription order; exceptions in
one subscriber don't affect others. ``stream()`` returns a queue-backed view
for async iteration in tests/user code.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Callable, Dict, Generic, TypeVar

T = TypeVar("T")
_log = logging.getLogger(__name__)


class EventStream(Generic[T]):
    def __init__(self) -> None:
        self._subs: Dict[int, Callable[[T], None]] = {}
        self._ids = itertools.count()

    def subscribe(self, handler: Callable[[T], None]) -> Callable[[], None]:
        sid = next(self._ids)
        self._subs[sid] = handler

        def unsubscribe() -> None:
            self._subs.pop(sid, None)

        return unsubscribe

    def emit(self, event: T) -> None:
        for handler in list(self._subs.values()):
            try:
                handler(event)
            except Exception:  # noqa: BLE001 - one bad subscriber must not break fan-out
                _log.exception("subscriber failed on %s", event)

    def stream(self) -> "asyncio.Queue[T]":
        q: asyncio.Queue[T] = asyncio.Queue()
        self.subscribe(q.put_nowait)
        return q
