"""Namespace validation and hierarchical relatedness.

Parity with reference namespace handling: validation regex
``^(\\w+[\\w\\-./]*\\w)+`` (``ClusterImpl.java:60,350``) and the
prefix-hierarchy membership gate ``areNamespacesRelated``
(``MembershipProtocolImpl.java:511-536``): two namespaces are related iff one
is a path-component prefix of the other (equal counts but different components
are unrelated).
"""

from __future__ import annotations

import re

_NAMESPACE_RE = re.compile(r"^(\w+[\w\-./]*\w)+$")


def is_valid_namespace(namespace: str) -> bool:
    """True if ``namespace`` matches the reference validation pattern."""
    return bool(_NAMESPACE_RE.match(namespace))


def validate_namespace(namespace: str) -> str:
    if not is_valid_namespace(namespace):
        raise ValueError(f"invalid cluster namespace format: {namespace!r}")
    return namespace


def _components(namespace: str) -> list:
    return [c for c in namespace.split("/") if c]


def are_namespaces_related(ns1: str, ns2: str) -> bool:
    """True iff ns1 == ns2 or one is a strict path-prefix of the other."""
    c1, c2 = _components(ns1), _components(ns2)
    if c1 == c2:
        return True
    if len(c1) == len(c2):
        return False
    shorter, longer = (c1, c2) if len(c1) < len(c2) else (c2, c1)
    return longer[: len(shorter)] == shorter
