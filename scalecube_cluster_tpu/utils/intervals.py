"""Closed-interval set for gossip sequence-id dedup.

Parity with reference ``SequenceIdCollector``
(``cluster/gossip/SequenceIdCollector.java:15-74``): an ordered set of closed
``[lo, hi]`` intervals; ``add`` returns False if the id was already present
and merges adjacent intervals; the interval count is the gossip-segmentation
signal (``GossipProtocolImpl.checkGossipSegmentation``, threshold
``GossipConfig.java:12``).

The vectorized kernel uses a dense received-seq bitmap instead; this class is
the scalar-engine implementation and the oracle for bitmap gap-count tests.
"""

from __future__ import annotations

import bisect
from typing import List, Tuple


class SequenceIdCollector:
    """Ordered disjoint closed-interval set over non-negative ints."""

    def __init__(self) -> None:
        # Sorted, disjoint, non-adjacent list of [lo, hi] closed intervals.
        self._intervals: List[List[int]] = []

    def add(self, seq_id: int) -> bool:
        """Insert ``seq_id``; returns True if it was new, False if duplicate."""
        iv = self._intervals
        # Find first interval with lo > seq_id.
        idx = bisect.bisect_right(iv, [seq_id, float("inf")])
        # Check containment in the interval before the insertion point.
        if idx > 0 and iv[idx - 1][1] >= seq_id:
            return False
        # Try to extend the previous interval (seq_id == prev.hi + 1).
        extend_prev = idx > 0 and iv[idx - 1][1] + 1 == seq_id
        # Try to extend the next interval (seq_id == next.lo - 1).
        extend_next = idx < len(iv) and iv[idx][0] - 1 == seq_id
        if extend_prev and extend_next:
            iv[idx - 1][1] = iv[idx][1]
            del iv[idx]
        elif extend_prev:
            iv[idx - 1][1] = seq_id
        elif extend_next:
            iv[idx][0] = seq_id
        else:
            iv.insert(idx, [seq_id, seq_id])
        return True

    def __contains__(self, seq_id: int) -> bool:
        iv = self._intervals
        idx = bisect.bisect_right(iv, [seq_id, float("inf")])
        return idx > 0 and iv[idx - 1][1] >= seq_id

    def size(self) -> int:
        """Number of disjoint intervals (the segmentation metric)."""
        return len(self._intervals)

    def clear(self) -> None:
        self._intervals.clear()

    def intervals(self) -> List[Tuple[int, int]]:
        return [(lo, hi) for lo, hi in self._intervals]

    def __repr__(self) -> str:
        return f"SequenceIdCollector({self._intervals})"
