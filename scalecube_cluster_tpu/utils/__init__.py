from . import cluster_math
from .intervals import SequenceIdCollector
from .namespaces import are_namespaces_related, is_valid_namespace, validate_namespace

__all__ = [
    "cluster_math",
    "SequenceIdCollector",
    "are_namespaces_related",
    "is_valid_namespace",
    "validate_namespace",
]
