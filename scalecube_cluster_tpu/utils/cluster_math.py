"""Closed-form SWIM math.

Function-for-function parity with reference ``ClusterMath``
(``cluster/ClusterMath.java:8-135``). These are pure scalar functions used as

* protocol knobs (gossip spread/sweep horizons, suspicion timeout),
* oracle for kernel tests, and
* expected-rounds curves for the benchmark harness.

All functions accept plain ints/floats and return plain values so they can be
used both host-side and (re-expressed in jnp where needed) inside the kernel.
"""

from __future__ import annotations

import math


def ceil_log2(num: int) -> int:
    """``ceil(log2(n + 1))`` via bit length — reference ClusterMath.java:133-135
    (``32 - numberOfLeadingZeros(num)``)."""
    if num < 0:
        raise ValueError("num must be >= 0")
    return int(num).bit_length()


def gossip_periods_to_spread(repeat_mult: int, cluster_size: int) -> int:
    """Rounds after which a rumor has most likely reached everyone
    (reference ClusterMath.java:111-113)."""
    return repeat_mult * ceil_log2(cluster_size)


def gossip_periods_to_sweep(repeat_mult: int, cluster_size: int) -> int:
    """Rounds after which a rumor is garbage-collected
    (reference ClusterMath.java:99-102)."""
    return 2 * (gossip_periods_to_spread(repeat_mult, cluster_size) + 1)


def gossip_dissemination_time(repeat_mult: int, cluster_size: int, gossip_interval: float) -> float:
    """Expected wall-clock dissemination time (reference ClusterMath.java:70-79)."""
    return gossip_periods_to_spread(repeat_mult, cluster_size) * gossip_interval


def gossip_timeout_to_sweep(repeat_mult: int, cluster_size: int, gossip_interval: float) -> float:
    """Wall-clock sweep timeout (reference ClusterMath.java:85-92)."""
    return gossip_periods_to_sweep(repeat_mult, cluster_size) * gossip_interval


def gossip_convergence_probability(
    fanout: int, repeat_mult: int, cluster_size: int, loss: float
) -> float:
    """P(everyone infected) under iid message loss
    (reference ClusterMath.java:38-44)."""
    fanout_with_loss = (1.0 - loss) * fanout
    spread_size = cluster_size - math.pow(cluster_size, -(fanout_with_loss * repeat_mult - 2))
    return spread_size / cluster_size


def gossip_convergence_percent(
    fanout: int, repeat_mult: int, cluster_size: int, loss_percent: float
) -> float:
    """Same as :func:`gossip_convergence_probability`, in percent
    (reference ClusterMath.java:22-27)."""
    return gossip_convergence_probability(fanout, repeat_mult, cluster_size, loss_percent / 100.0) * 100.0


def max_messages_per_gossip_per_node(fanout: int, repeat_mult: int, cluster_size: int) -> int:
    """Upper bound on per-node messages for one rumor
    (reference ClusterMath.java:54-67)."""
    return fanout * repeat_mult * ceil_log2(cluster_size)


def max_messages_per_gossip_total(fanout: int, repeat_mult: int, cluster_size: int) -> int:
    """Cluster-wide message bound for one rumor (reference ClusterMath.java:47-52)."""
    return cluster_size * max_messages_per_gossip_per_node(fanout, repeat_mult, cluster_size)


def suspicion_timeout(suspicion_mult: int, cluster_size: int, ping_interval: float) -> float:
    """Suspicion timeout before a SUSPECT member is declared DEAD
    (reference ClusterMath.java:123-125)."""
    return suspicion_mult * ceil_log2(cluster_size) * ping_interval
