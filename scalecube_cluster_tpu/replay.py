"""Incident replay + counterfactual what-if service (r18 tentpole).

A flight dump (telemetry/flight.py, schema 2) embeds a ``reconstruction``
section: everything needed to rebuild the dying run — engine, params doc,
construction seed, the armed scenario's event timeline, and the recorded
sentinel verdict. This module turns that artifact into three things:

1. **An incident** (:func:`incident_from_flight`): the reconstruction
   section decoded back into live objects — a params dataclass, a
   :class:`..chaos.Scenario`, the seed/t0/max_window the original run used.

2. **A validated replay** (:func:`validate_incident`): a fresh
   :class:`..sim.SimDriver` built from the incident, pre-stepped to the
   recorded ``t0``, re-running the scenario serially. The per-tick key
   chain depends only on the total tick count (``key, k = split(key)``
   once per tick inside the scan), so a replay from the same construction
   seed walks the same chain and must reproduce the recorded verdict —
   the round-trip check that certifies the reconstruction is faithful,
   not merely plausible. (Drivers whose pre-arm history was more than
   stepping — API joins, transport sends — replay the scenario from a
   different pre-state; the verdict comparison then reports
   ``reproduced: False`` rather than pretending.)

3. **A counterfactual benchmark** (:func:`whatif`): the incident replayed
   as a scenario-batched fleet (r15 engine) across alternative knob
   settings — fanout, suspicion multiplier, FD cadence, dissemination
   strategy/topology, adaptive-FD spec — ≥256 seeds per arm, the full
   on-device sentinel program vmapped over the fleet, per-arm Wilson
   intervals on P(all sentinels green) + zero-false-DEAD (the same
   discipline as ``control.certify_controller_mc``). Every arm runs the
   SAME seed vector, so interval separation is a paired comparison: an
   arm whose interval is disjoint from the as-recorded arm's is a
   certified "this knob change would have mattered", not noise. The
   monitor serves the newest record at ``GET /whatif``
   (:class:`WhatifService`); ``benchmarks/config17_replay.py`` writes it
   as REPLAY_BENCH_r18.json.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from .chaos.events import Scenario, ScenarioError, scenario_from_dict
from .telemetry.flight import load_flight_dump


class ReplayError(RuntimeError):
    """An artifact or arm spec the replay service refuses: a pre-r18 dump
    with no reconstruction section, a missing seed, an unknown knob."""


# ---------------------------------------------------------------------------
# incident reconstruction
# ---------------------------------------------------------------------------

#: arm-override keys that are NOT direct params fields (handled specially)
_SPEC_KNOBS = ("strategy", "topology", "dissem", "adaptive")


@dataclasses.dataclass
class Incident:
    """One reconstructed flight: live objects, ready to re-run."""

    engine: str
    params: object  # SimParams / SparseParams / PviewParams
    scenario: Scenario
    seed: int
    n_initial: int
    dense_links: bool
    warm: bool
    t0: int
    max_window: int
    sentinels_armed: bool
    verdict: Optional[dict]  # {"ok", "violations", "ticks_run"} or None
    reason: str = ""
    source: Optional[str] = None  # the dump path, when loaded from disk


def _params_class(engine: str):
    if engine == "dense":
        from .ops.state import SimParams

        return SimParams
    if engine == "sparse":
        from .ops.sparse import SparseParams

        return SparseParams
    if engine == "pview":
        from .ops.pview import PviewParams

        return PviewParams
    raise ReplayError(f"reconstruction names unknown engine {engine!r}")


def params_from_doc(engine: str, doc: dict):
    """Rebuild the params dataclass from its ``dataclasses.asdict`` JSON
    round-trip: nested dissem/adaptive specs become their dataclasses
    again, JSON lists become the tuples the frozen params expect, and
    fields this build does not know are dropped LOUDLY (a dump from a
    newer build is refused at the schema gate before we ever get here,
    so an unknown field means a hand-edited artifact)."""
    cls = _params_class(engine)
    known = {f.name for f in dataclasses.fields(cls)}
    unknown = sorted(set(doc) - known)
    if unknown:
        raise ReplayError(
            f"params doc carries fields {unknown} the {engine} engine's "
            f"{cls.__name__} does not have — refusing a partial rebuild"
        )
    kwargs = {}
    for k, v in doc.items():
        if k == "dissem" and isinstance(v, dict):
            from .dissemination.spec import DissemSpec

            kwargs[k] = DissemSpec(**v)
        elif k == "adaptive" and isinstance(v, dict):
            from .adaptive import AdaptiveSpec

            kwargs[k] = AdaptiveSpec(**v)
        elif isinstance(v, list):
            kwargs[k] = tuple(v)
        else:
            kwargs[k] = v
    return cls(**kwargs)


def _reconstruction_of(dump) -> tuple:
    """(reconstruction dict, source path or None, reason) from a dump path
    or an already-loaded document — with the loud pre-r18 refusal."""
    source = None
    if isinstance(dump, str):
        source = dump
        dump = load_flight_dump(dump)
    rec = dump.get("reconstruction", "partial")
    if not isinstance(rec, dict):
        raise ReplayError(
            "flight dump has reconstruction: 'partial' — it predates the "
            "r18 schema (or its writer had no armed chaos runner), so "
            "there is no event timeline to replay"
        )
    return rec, source, str(dump.get("reason", ""))


def scenario_from_flight(dump) -> Scenario:
    """Rebuild just the replayable :class:`..chaos.Scenario` from a dump
    (path or loaded doc). The full driver-rebuild inputs come via
    :func:`incident_from_flight`."""
    rec, _source, _reason = _reconstruction_of(dump)
    return scenario_from_dict(rec["scenario"])


def incident_from_flight(dump) -> Incident:
    """Decode a schema-2 dump's reconstruction section into an
    :class:`Incident`. Refuses partial dumps and seed-less recorders
    (a restored-from-pickle driver predating the r18 seed stamp)."""
    rec, source, reason = _reconstruction_of(dump)
    if rec.get("seed") is None:
        raise ReplayError(
            "reconstruction carries no construction seed — the recording "
            "driver predates the r18 seed stamp; the replay cannot walk "
            "the same PRNG chain"
        )
    engine = rec["engine"]
    return Incident(
        engine=engine,
        params=params_from_doc(engine, rec["params"]),
        scenario=scenario_from_dict(rec["scenario"]),
        seed=int(rec["seed"]),
        n_initial=int(rec["n_initial"]),
        dense_links=bool(rec.get("dense_links", True)),
        warm=bool(rec.get("warm", True)),
        t0=int(rec.get("t0", 0)),
        max_window=int(rec.get("max_window", 32)),
        sentinels_armed=bool(rec.get("sentinels_armed", True)),
        verdict=rec.get("verdict"),
        reason=reason,
        source=source,
    )


def validate_incident(incident: Incident, *, config=None) -> dict:
    """Re-run the incident serially on a fresh driver and compare the
    sentinel verdict to the recorded one. Pre-steps the driver to the
    recorded ``t0`` first — the per-tick key chain depends only on tick
    count, so a pure-stepping pre-arm history replays bit-identically."""
    from .sim.driver import SimDriver

    d = SimDriver(
        incident.params,
        incident.n_initial,
        warm=incident.warm,
        seed=incident.seed,
        dense_links=incident.dense_links,
    )
    if incident.t0 > 0:
        d.step(incident.t0)
    report = d.run_scenario(
        incident.scenario,
        config=config,
        sentinels=incident.sentinels_armed,
        max_window=incident.max_window,
    )
    recorded = incident.verdict
    reproduced = None
    if recorded is not None:
        reproduced = (
            bool(report["ok"]) == bool(recorded["ok"])
            and int(report["violations"]) == int(recorded["violations"])
        )
    return {
        "scenario": incident.scenario.name,
        "engine": incident.engine,
        "seed": incident.seed,
        "t0": incident.t0,
        "recorded": recorded,
        "replayed": {
            "ok": bool(report["ok"]),
            "violations": int(report["violations"]),
            "ticks_run": int(report["ticks_run"]),
        },
        "reproduced": reproduced,
        "report": report,
    }


# ---------------------------------------------------------------------------
# counterfactual arms
# ---------------------------------------------------------------------------


def arm_params(incident: Incident, arm: dict):
    """Apply one arm's knob overrides to the incident's params.

    The arm grammar: ``{"name": ..., <overrides>}`` where overrides are
    direct params fields (``fanout``, ``suspicion_mult``, ``fd_every``, …),
    ``strategy``/``topology`` (merged into the dissem spec), ``dissem``
    (a dict of DissemSpec fields), or ``adaptive`` (AdaptiveSpec fields).
    Unknown knobs are refused — a typo'd arm must not silently benchmark
    the as-recorded configuration under a counterfactual name."""
    doc = {k: v for k, v in arm.items() if k != "name"}
    base = incident.params
    fields = {f.name for f in dataclasses.fields(base)}
    knobs: Dict[str, object] = {}
    if any(k in doc for k in ("strategy", "topology", "dissem")):
        if "dissem" not in fields:
            raise ReplayError(
                f"arm {arm.get('name')!r} overrides dissemination, but the "
                f"{incident.engine} engine's params carry no dissem spec"
            )
        from .dissemination.spec import DissemSpec

        dd = dataclasses.asdict(getattr(base, "dissem") or DissemSpec())
        dd.update(doc.pop("dissem", {}) or {})
        if "strategy" in doc:
            dd["strategy"] = doc.pop("strategy")
        if "topology" in doc:
            dd["topology"] = doc.pop("topology")
        knobs["dissem"] = DissemSpec(**dd)
    if "adaptive" in doc:
        if "adaptive" not in fields:
            raise ReplayError(
                f"arm {arm.get('name')!r} overrides adaptive FD, but the "
                f"{incident.engine} engine's params carry no adaptive spec"
            )
        from .adaptive import AdaptiveSpec

        ad = dataclasses.asdict(getattr(base, "adaptive") or AdaptiveSpec())
        ad.update(doc.pop("adaptive") or {})
        knobs["adaptive"] = AdaptiveSpec(**ad)
    for k, v in doc.items():
        if k not in fields:
            raise ReplayError(
                f"arm {arm.get('name')!r} overrides unknown knob {k!r} "
                f"(not a {type(base).__name__} field)"
            )
        knobs[k] = v
    return dataclasses.replace(base, **knobs)


def _run_arm_fleet(
    incident: Incident,
    params,
    *,
    n_seeds: int,
    base_seed: int,
    window: int,
    conf: float,
) -> dict:
    """One arm: ``n_seeds`` fleet replays of the incident's scenario under
    ``params``, the engine's sentinel program vmapped over the scenario
    axis. All folds stay on device; ONE readback of the [S]-shaped
    accumulators at the end, then the sentinel_report judgment rules
    (chaos/sentinels.py) applied vectorized per seed."""
    import jax
    import jax.numpy as jnp

    from .chaos.sentinels import build_spec
    from .ops import engine_api
    from .ops import fleet as FL

    eng = engine_api.resolve(params)
    n = incident.n_initial
    scenario = incident.scenario
    spec = build_spec(scenario, params)
    horizon = spec.horizon
    aspec = getattr(params, "adaptive", None)
    adaptive = aspec is not None and not aspec.is_default
    # the r15 fleet discipline: batched-predicate conds materialize selects
    # over every state leaf, so fleet callers statically trace the active
    # branch (value-identical — quiet gates are dispatch-cost only)
    if "quiet_gates" in {f.name for f in dataclasses.fields(params)}:
        params = dataclasses.replace(params, quiet_gates=False)

    st0 = eng.init_state(params, n, incident.warm, incident.dense_links)
    fs = FL.fleet_broadcast(st0, n_seeds)
    keys = FL.fleet_keys(base_seed + np.arange(n_seeds))
    ad = None
    if adaptive:
        from .adaptive import init_adaptive_state

        ad = FL.fleet_broadcast(init_adaptive_state(params.capacity), n_seeds)
    try:
        tl = FL.fleet_timeline(
            scenario, eng.ops, dense_links=incident.dense_links,
            horizon=horizon,
        )
    except ScenarioError as exc:
        # engine-capability refusal (e.g. DroppedRefute off-dense): surface
        # as a ReplayError carrying the incident context — the underlying
        # message already names the offending event and engine
        raise ReplayError(
            f"incident {scenario.name!r} cannot replay on the "
            f"{incident.engine!r} engine: {exc}"
        ) from exc
    sent = jax.vmap(lambda st: eng.sentinel_init(st, spec))(fs)
    spec_dev = spec.device_arrays(0)
    check_fn = jax.jit(jax.vmap(eng.sentinel_reduce, in_axes=(0, 0, None)))
    progs: Dict[int, object] = {}

    def _prog(k_ticks: int):
        if k_ticks not in progs:
            progs[k_ticks] = (
                FL.make_fleet_adaptive_run(params, k_ticks) if adaptive
                else FL.make_fleet_run(params, k_ticks)
            )
        return progs[k_ticks]

    boundaries = set(tl.boundaries())
    check_every = spec.check_interval
    next_check = check_every
    t = 0
    while True:
        # events due at t apply BEFORE the sentinel sample at t (the
        # DriverChaosRunner ordering — a same-tick heal is judged healed)
        fs, _labels = tl.apply_due(fs, t)
        if t >= next_check or t >= horizon:
            sent = check_fn(fs, sent, spec_dev)
            next_check = t + check_every
        if t >= horizon:
            break
        stops = [horizon, t + window, next_check] + [
            b for b in boundaries if b > t
        ]
        stop = min(s for s in stops if s > t)
        if adaptive:
            fs, ad, keys, _ms, _w = _prog(stop - t)(fs, ad, keys)
        else:
            fs, keys, _ms, _w = _prog(stop - t)(fs, keys)
        t = stop

    # THE readback: every accumulator comes to host as one [S]-leading batch
    sent_np = {k: np.asarray(v) for k, v in sent.items()}

    # sentinel_report's judgment rules, vectorized over the seed axis
    det = sent_np["detect_tick"].reshape(n_seeds, -1)  # [S, K]
    d_dl = spec.crash_deadline[None, :]
    d_judged = (horizon >= spec.crash_deadline) & (
        spec.crash_until >= spec.crash_deadline
    )
    ok_det = (((det >= 0) & (det <= d_dl)) | ~d_judged[None, :]).all(axis=1)
    conv = sent_np["conv_tick"].reshape(n_seeds, -1)  # [S, C]
    c_dl = spec.conv_deadline[None, :]
    c_judged = horizon >= spec.conv_deadline
    ok_conv = (((conv >= 0) & (conv <= c_dl)) | ~c_judged[None, :]).all(axis=1)
    false_dead = sent_np["false_dead_max"].reshape(n_seeds)
    regress = sent_np["key_regressions"].reshape(n_seeds)
    ok = ok_det & ok_conv & (false_dead == 0) & (regress == 0)
    fp = None
    if "fp_dead_max" in sent_np and spec.fp_enforce:
        fp = sent_np["fp_dead_max"].reshape(n_seeds)
        ok = ok & (fp == 0)
    for extra in ("n_live_drift", "view_invariant_breaks"):
        if extra in sent_np:
            ok = ok & (sent_np[extra].reshape(n_seeds) == 0)

    from .dissemination.certify import MC_MIN_SAMPLES, wilson_interval

    k_ok = int(ok.sum())
    wil = wilson_interval(k_ok, n_seeds, conf)
    lat = det - spec.crash_at[None, :]
    lat = np.sort(lat[(det >= 0) & d_judged[None, :]])
    return {
        "n_seeds": n_seeds,
        "sample_size": n_seeds,
        "verdict_kind": (
            "monte-carlo" if n_seeds >= MC_MIN_SAMPLES else "spot-check"
        ),
        "horizon": int(horizon),
        "detect_budget": int(spec.detect_budget),
        "converge_budget": int(spec.converge_budget),
        "check_interval": int(check_every),
        "green": k_ok,
        "p_green": round(k_ok / n_seeds, 6),
        "wilson": [round(wil[0], 6), round(wil[1], 6)],
        "interval_method": f"Wilson {conf:.0%} on P(all sentinels green)",
        "fail_detect": int((~ok_det).sum()),
        "fail_converge": int((~ok_conv).sum()),
        "false_dead_scenarios": int((false_dead > 0).sum()),
        "key_regression_scenarios": int((regress > 0).sum()),
        "false_positive_scenarios": (
            int((fp > 0).sum()) if fp is not None else None
        ),
        "zero_false_dead": bool((false_dead == 0).all()),
        "detect_latency_p50": float(np.median(lat)) if lat.size else None,
        "detect_latency_max": int(lat[-1]) if lat.size else None,
    }


def whatif(
    incident: Incident,
    arms: Sequence[dict] = (),
    *,
    seeds_per_arm: int = 256,
    base_seed: int = 1000,
    window: Optional[int] = None,
    conf: float = 0.95,
    log=None,
) -> dict:
    """The counterfactual benchmark: replay the incident's scenario as a
    fleet under the as-recorded knobs AND every counterfactual arm, same
    seed vector throughout (paired comparison), per-arm Wilson intervals
    on P(all sentinels green). An arm whose interval is DISJOINT from the
    as-recorded arm's is CI-separated: a certified would-have-mattered.

    Returns the REPLAY_BENCH_r18.json record."""
    import os

    import jax

    if seeds_per_arm < 1:
        raise ReplayError("seeds_per_arm must be >= 1")
    window = window or incident.max_window
    named = set()
    for arm in arms:
        name = arm.get("name")
        if not name or name == "as-recorded":
            raise ReplayError(
                "every counterfactual arm needs a unique name (and "
                "'as-recorded' is reserved for the baseline arm)"
            )
        if name in named:
            raise ReplayError(f"duplicate arm name {name!r}")
        named.add(name)

    def _one(name: str, params, overrides) -> dict:
        rec = _run_arm_fleet(
            incident, params,
            n_seeds=seeds_per_arm, base_seed=base_seed,
            window=window, conf=conf,
        )
        rec["arm"] = name
        rec["overrides"] = overrides
        if log:
            log(
                f"{incident.scenario.name}/{name}: P(green) "
                f"{rec['p_green']} wilson {rec['wilson']} "
                f"fp {rec['false_dead_scenarios']}"
            )
        return rec

    baseline = _one("as-recorded", incident.params, {})
    entries = [baseline]
    for arm in arms:
        overrides = {k: v for k, v in arm.items() if k != "name"}
        entries.append(_one(arm["name"], arm_params(incident, arm), overrides))

    lo0, hi0 = baseline["wilson"]
    n_separated = 0
    for rec in entries[1:]:
        lo, hi = rec["wilson"]
        if lo > hi0:
            rec["separated"] = "better"
        elif hi < lo0:
            rec["separated"] = "worse"
        else:
            rec["separated"] = None
        n_separated += rec["separated"] is not None
    baseline["separated"] = None

    return {
        "scenario": incident.scenario.name,
        "engine": incident.engine,
        "n": incident.n_initial,
        "incident": {
            "reason": incident.reason,
            "source": incident.source,
            "seed": incident.seed,
            "t0": incident.t0,
            "recorded_verdict": incident.verdict,
        },
        "n_arms": len(entries),
        "seeds_per_arm": seeds_per_arm,
        "window_ticks": window,
        "conf": conf,
        # provenance stamps (the r13 rule): backend + host CPUs + the
        # relative tick span every arm replayed
        "backend": jax.default_backend(),
        "host_cpus": os.cpu_count(),
        "tick_range": [0, int(entries[0]["horizon"])],
        "arms": entries,
        "as_recorded_wilson": baseline["wilson"],
        "n_separated": n_separated,
        "any_arm_separated": n_separated > 0,
    }


# ---------------------------------------------------------------------------
# the monitor-served service
# ---------------------------------------------------------------------------


class WhatifService:
    """Holds the newest what-if record for ``GET /whatif``.

    The MC computation is minutes of fleet windows — far outside an HTTP
    GET budget — so the monitor serves the LAST computed record (like
    ``/chaos`` serves the last report), and :meth:`run` is the explicit
    compute step an operator (or bench harness) invokes."""

    def __init__(self, incident: Optional[Incident] = None):
        self.record: dict = {"computed": False}
        self.history: List[dict] = []
        #: r19: the LIVE incident operator-submitted arm ladders run
        #: against (``POST /whatif``); None keeps the service GET-only
        self.incident = incident

    def attach_incident(self, incident: Incident) -> None:
        """Arm ``POST /whatif`` with a (new) live incident."""
        self.incident = incident

    def run(self, incident: Incident, arms: Sequence[dict] = (), **kw) -> dict:
        rec = whatif(incident, arms, **kw)
        rec["computed"] = True
        self.record = rec
        self.history.append(rec)
        return rec

    def run_operator(self, doc: dict) -> dict:
        """Operator entry behind ``POST /whatif``: an arm ladder document
        ``{"arms": [{"name": ..., <knob>: ...}, ...], "seeds_per_arm"?,
        "conf"?}`` validated EAGERLY with the existing refusal grammar —
        every arm passes through :func:`arm_params` (unknown-knob refusal)
        before a single MC seed is paid, and :func:`whatif` applies its own
        reserved-name / duplicate-name refusals — then run against the live
        incident."""
        if self.incident is None:
            raise ReplayError(
                "no live incident attached — construct "
                "WhatifService(incident=...) or call attach_incident() "
                "before POSTing arm ladders"
            )
        if not isinstance(doc, dict):
            raise ReplayError("POST /whatif body must be a JSON object")
        arms = doc.get("arms")
        if not isinstance(arms, list) or not arms:
            raise ReplayError(
                "POST /whatif needs a non-empty 'arms' list of "
                "{'name': ..., <knob>: ...} objects"
            )
        for arm in arms:
            if not isinstance(arm, dict):
                raise ReplayError(f"arm {arm!r} is not an object")
            if isinstance(arm.get("name"), str) and arm["name"]:
                # eager unknown-knob refusal: a typo'd knob must refuse
                # BEFORE the as-recorded baseline fleet runs
                arm_params(self.incident, arm)
        kw: dict = {}
        if "seeds_per_arm" in doc:
            kw["seeds_per_arm"] = int(doc["seeds_per_arm"])
        if "conf" in doc:
            kw["conf"] = float(doc["conf"])
        return self.run(self.incident, arms, **kw)

    def snapshot(self) -> dict:
        return self.record
